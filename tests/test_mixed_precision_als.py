"""Mixed-precision (bf16-Gram) ALS: phase-1 bulk + exact polish reaches the
exact fixed point; bf16-only core lands near it."""

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.dfm import DFMConfig, estimate_factor


@pytest.fixture
def panel():
    rng = np.random.default_rng(21)
    T, N, r = 160, 40, 3
    f = np.zeros((T, r))
    for t in range(1, T):
        f[t] = 0.8 * f[t - 1] + rng.standard_normal(r)
    lam = rng.standard_normal((N, r))
    x = f @ lam.T + rng.standard_normal((T, N))
    # keep the first 10 columns fully observed (PCA init needs a balanced
    # block), knock 10% of cells out of the rest
    miss = rng.random((T, N)) < 0.1
    miss[:, :10] = False
    x[miss] = np.nan
    return x


def test_mixed_precision_reaches_exact_fixed_point(panel):
    cfg = DFMConfig(nfac_u=3, nt_min_factor=20)
    f32, fes32 = estimate_factor(panel, np.ones(panel.shape[1]), 0, panel.shape[0] - 1, cfg)
    fmix, fesmix = estimate_factor(
        panel, np.ones(panel.shape[1]), 0, panel.shape[0] - 1, cfg,
        gram_dtype="bfloat16",
    )
    # the polish phase must land on the exact map's fixed point: SSR equal
    # to the pure-exact run at convergence-tolerance level
    ssr32, ssrmix = float(fes32.ssr), float(fesmix.ssr)
    assert abs(ssrmix - ssr32) <= 1e-4 * ssr32, (ssr32, ssrmix)
    # factors identical up to column sign at tight tolerance
    a, b = np.nan_to_num(np.asarray(f32)), np.nan_to_num(np.asarray(fmix))
    s = np.sign((a * b).sum(axis=0)); s[s == 0] = 1.0
    assert np.abs(a - b * s).max() < 5e-3 * np.abs(a).max()
    # n_iter counts both phases
    assert int(fesmix.n_iter) >= int(fes32.n_iter) and int(fesmix.n_iter) > 0


def test_bf16_core_runs_and_lands_near(panel):
    from dynamic_factor_models_tpu.models.dfm import _als_core
    from dynamic_factor_models_tpu.ops.linalg import pca_score, standardize_data
    from dynamic_factor_models_tpu.ops.masking import fillz, mask_of

    xj = jnp.asarray(panel)
    xstd, _ = standardize_data(xj)
    xz, m = fillz(xstd), mask_of(xstd).astype(xstd.dtype)
    f0 = pca_score(jnp.where(jnp.isnan(xstd), 0.0, xstd), 3)
    lam_ok = jnp.ones(panel.shape[1], bool)
    args = (xz, m, lam_ok, f0, jnp.asarray(0.0, xz.dtype), 3, 50)
    f_exact, _, ssr_exact, _ = _als_core(*args)
    f_bf16, _, ssr_bf16, _ = _als_core(*args, gram_dtype="bfloat16")
    # bf16 Grams perturb the map at operand precision: nearby, not equal
    rel = abs(float(ssr_bf16) - float(ssr_exact)) / float(ssr_exact)
    assert rel < 2e-2, rel
    assert f_bf16.dtype == xz.dtype


def test_mixed_precision_shares_iteration_budget(panel):
    """The two phases share max_iter: n_iter stays a valid budget/
    convergence flag (+1 only when the bulk phase exhausts the cap)."""
    cfg = DFMConfig(nfac_u=3, nt_min_factor=20)
    cap = 6
    _, fes = estimate_factor(
        panel, np.ones(panel.shape[1]), 0, panel.shape[0] - 1, cfg,
        max_iter=cap, gram_dtype="bfloat16",
    )
    assert int(fes.n_iter) <= cap + 1, int(fes.n_iter)


def test_gram_dtype_validation(panel):
    cfg = DFMConfig(nfac_u=3, nt_min_factor=20)
    with pytest.raises(ValueError, match="gram_dtype"):
        estimate_factor(
            panel, np.ones(panel.shape[1]), 0, panel.shape[0] - 1, cfg,
            gram_dtype="float16",
        )
