"""Dual-form burst catch-up: GEMM prefill for every replay path (PR:
dual-form prefill).

Pinned claims:

1. the GEMM dual (serving/prefill.prefill_ticks) matches sequential
   `replay_ticks` to <=1e-14 (complete, d=1) and <=1e-12 (MF period-3)
   at EVERY power-of-two depth 1..1024 and at ragged depths — prime k,
   k=1, and k past the top bucket (chunked) — from every start phase;
2. MF period-3 phase alignment survives block boundaries: one backlog
   prefilled in two chunks equals the single-chunk result, from any
   phase;
3. a degenerate pre-t* tenant falls back to sequential replay LOUDLY
   (RuntimeWarning + counter) and bit-identically;
4. short backlogs (< DFM_PREFILL_MIN_K) and the DFM_PREFILL=0 escape
   hatch stay BITWISE equal to sequential replay;
5. the decode-form block (`tick_block`) is bitwise equal to sequential
   single-tick dispatches, per row, including bucket padding — and a
   deep flush_period backlog rides it bitwise-equal to sequential
   handle() ticks with contiguous journal tick indices;
6. the PR 13 crash_io kill matrix holds on the prefill replay path:
   restart from a deep (GEMM-threshold) journal recovers acked <=
   recovered <= acked + in-flight, second restart bit-identical;
7. recover(prewarm) routes deep journals through the lane-batched GEMM
   prefill and lands within dual-parity of the sequential replay;
8. `telemetry summarize` renders the prefill columns (blocks,
   ticks-per-prefill p50) and "-" for pre-PR-20 sinks;
9. CompileSpec.prefill_depth registers serving_prefill@K{2^j} /
   serving_tick_block@K{2^j} AOT plans for every bucket up to the
   declared depth.
"""

import json

import numpy as np
import pytest

from dynamic_factor_models_tpu.serving import prefill as pf
from dynamic_factor_models_tpu.serving.batch import batched_prefill_dispatch
from dynamic_factor_models_tpu.serving.engine import ServingEngine
from dynamic_factor_models_tpu.serving.online import (
    FilterState,
    ServingModel,
    online_tick,
    replay_ticks,
)
from dynamic_factor_models_tpu.serving.resilience import RetryPolicy
from dynamic_factor_models_tpu.utils import faults, telemetry
from dynamic_factor_models_tpu.utils.compile import CompileSpec, _kernel_plan

import jax.numpy as jnp

pytestmark = [pytest.mark.serving, pytest.mark.prefill]

_POLICY = RetryPolicy(max_retries=2, backoff_base_s=0.0)

T, N = 48, 6


def _panel(seed=0):
    rng = np.random.default_rng(seed)
    f = rng.standard_normal((T, 4)).cumsum(0) * 0.1
    lam = rng.standard_normal((N, 4))
    return f @ lam.T + 0.5 * rng.standard_normal((T, N))


def _engine(store_dir=None, **kw):
    kw.setdefault("retry_policy", _POLICY)
    kw.setdefault("max_em_iter", 5)
    return ServingEngine(store_dir=store_dir, **kw)


def _mk_model(d, kdim=6, q=None, Nn=7, seed=0):
    """Synthetic stable constant-gain model: parity is a property of the
    recursion, not of where the gains came from."""
    rng = np.random.default_rng(seed)
    if q is None:
        q = 3 if d == 1 else 15
    Abar = rng.standard_normal((d, kdim, kdim))
    for j in range(d):  # spectral radius well under 1
        Abar[j] *= 0.6 / max(1.0, np.max(np.abs(np.linalg.eigvals(Abar[j]))))
    return ServingModel(
        Wb=jnp.asarray(0.3 * rng.standard_normal((Nn, q))),
        H=jnp.asarray(0.3 * rng.standard_normal((Nn, q))),
        Tm=jnp.asarray(np.eye(kdim) * 0.5),
        Abar=jnp.asarray(Abar),
        K=jnp.asarray(0.2 * rng.standard_normal((d, kdim, q))),
    )


def _mk_rows(model, k, seed=1, base_t=0, holes=True):
    rng = np.random.default_rng(seed)
    Nn = model.Wb.shape[0]
    rows = []
    for i in range(k):
        x = rng.standard_normal(Nn)
        m = (
            rng.random(Nn) > 0.2 if holes else np.ones(Nn, bool)
        )
        rows.append((base_t + i, np.where(m, x, 0.0), m))
    return rows


def _state(model, t, seed=2):
    rng = np.random.default_rng(seed)
    kdim = model.Abar.shape[1]
    return FilterState(
        s=jnp.asarray(rng.standard_normal(kdim)),
        t=jnp.asarray(t, jnp.int32),
    )


def _rel_err(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return np.max(np.abs(a - b)) / max(1.0, np.max(np.abs(b)))


# ---------------------------------------------------------------------------
# 1. GEMM dual == sequential replay at every depth
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("k", list(pf.PREFILL_BUCKETS))
def test_gemm_parity_complete_every_power_of_two(k, monkeypatch):
    monkeypatch.setenv("DFM_PREFILL_MIN_K", "1")
    model = _mk_model(d=1, seed=k)
    state = _state(model, t=17, seed=k + 1)
    rows = _mk_rows(model, k, seed=k + 2, base_t=17)
    got = pf.prefill_ticks(model, state, rows)
    ref = replay_ticks(model, state, rows)
    assert int(got.t) == int(ref.t) == 17 + k
    assert _rel_err(got.s, ref.s) <= 1e-14, (k, _rel_err(got.s, ref.s))


@pytest.mark.parametrize("k", [1, 2, 3, 5, 7, 97, 509, 1024])
@pytest.mark.parametrize("phase", [0, 1, 2])
def test_gemm_parity_mf_period3_ragged_and_phases(k, phase, monkeypatch):
    monkeypatch.setenv("DFM_PREFILL_MIN_K", "1")
    model = _mk_model(d=3, seed=5)
    t0 = 30 + phase  # start phase = t0 % 3
    state = _state(model, t=t0, seed=6)
    rows = _mk_rows(model, k, seed=7, base_t=t0)
    got = pf.prefill_ticks(model, state, rows)
    ref = replay_ticks(model, state, rows)
    assert int(got.t) == int(ref.t) == t0 + k
    assert _rel_err(got.s, ref.s) <= 1e-12, (k, phase, _rel_err(got.s, ref.s))


def test_gemm_parity_chunked_past_top_bucket(monkeypatch):
    monkeypatch.setenv("DFM_PREFILL_MIN_K", "1")
    k = pf.MAX_PREFILL_DEPTH + 476  # forces two chunks, second ragged
    for d in (1, 3):
        model = _mk_model(d=d, kdim=4, seed=11 + d)
        state = _state(model, t=9, seed=12)
        rows = _mk_rows(model, k, seed=13, base_t=9)
        got = pf.prefill_ticks(model, state, rows)
        ref = replay_ticks(model, state, rows)
        assert int(got.t) == 9 + k
        tol = 1e-14 if d == 1 else 1e-12
        assert _rel_err(got.s, ref.s) <= tol


# ---------------------------------------------------------------------------
# 2. MF phase alignment across block boundaries
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("phase", [0, 1, 2])
@pytest.mark.parametrize("split", [1, 2, 3, 7])
def test_mf_phase_alignment_across_block_boundaries(phase, split, monkeypatch):
    """Prefilling one backlog in two blocks must thread the period-3
    phase through the boundary: (k1, k2) chunks == one k1+k2 chunk, for
    every start phase and non-cycle-aligned split."""
    monkeypatch.setenv("DFM_PREFILL_MIN_K", "1")
    model = _mk_model(d=3, seed=21)
    t0 = 60 + phase
    state = _state(model, t=t0, seed=22)
    rows = _mk_rows(model, 16, seed=23, base_t=t0)
    whole = pf.prefill_ticks(model, state, rows)
    mid = pf.prefill_ticks(model, state, rows[:split])
    two = pf.prefill_ticks(model, mid, rows[split:])
    assert int(two.t) == int(whole.t)
    assert _rel_err(two.s, whole.s) <= 1e-12
    ref = replay_ticks(model, state, rows)
    assert _rel_err(two.s, ref.s) <= 1e-12


# ---------------------------------------------------------------------------
# 3. pre-t* fallback is loud, counted, and bitwise
# ---------------------------------------------------------------------------


def test_pre_tstar_falls_back_loudly_and_bitwise(monkeypatch):
    monkeypatch.setenv("DFM_PREFILL_MIN_K", "1")
    telemetry.reset()
    model = _mk_model(d=1, seed=31)
    state = _state(model, t=4, seed=32)
    rows = _mk_rows(model, 12, seed=33, base_t=4)
    with pytest.warns(RuntimeWarning, match="before the steady-state"):
        got = pf.prefill_ticks(model, state, rows, t_star=40)
    ref = replay_ticks(model, state, rows)
    np.testing.assert_array_equal(np.asarray(got.s), np.asarray(ref.s))
    assert telemetry._counters.get("serving.prefill.pre_tstar_fallback") == 1
    # past t* the same call takes the dual (no warning)
    state2 = _state(model, t=60, seed=32)
    rows2 = _mk_rows(model, 12, seed=33, base_t=60)
    got2 = pf.prefill_ticks(model, state2, rows2, t_star=40)
    assert _rel_err(got2.s, replay_ticks(model, state2, rows2).s) <= 1e-14


def test_short_and_disabled_paths_are_bitwise(monkeypatch):
    model = _mk_model(d=3, seed=41)
    state = _state(model, t=33, seed=42)
    short = _mk_rows(model, pf.min_gemm_depth() - 1, seed=43, base_t=33)
    got = pf.prefill_ticks(model, state, short)
    np.testing.assert_array_equal(
        np.asarray(got.s), np.asarray(replay_ticks(model, state, short).s)
    )
    monkeypatch.setenv("DFM_PREFILL", "0")
    deep = _mk_rows(model, 64, seed=44, base_t=33)
    got = pf.prefill_ticks(model, state, deep)
    np.testing.assert_array_equal(
        np.asarray(got.s), np.asarray(replay_ticks(model, state, deep).s)
    )


# ---------------------------------------------------------------------------
# 4. decode-form block: bitwise per row
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("k", [1, 2, 5, 8, 13])
def test_tick_block_is_bitwise_per_row(k):
    model = _mk_model(d=3, seed=51)
    state = _state(model, t=12, seed=52)
    rows = _mk_rows(model, k, seed=53, base_t=12)
    final, per_row = pf.tick_block(model, state, rows)
    assert len(per_row) == k
    st = state
    for i, (_t, x, m) in enumerate(rows):
        st = online_tick(model, st, x, m)
        np.testing.assert_array_equal(
            np.asarray(per_row[i].s), np.asarray(st.s)
        )
        assert int(per_row[i].t) == int(st.t)
    np.testing.assert_array_equal(np.asarray(final.s), np.asarray(st.s))


def test_deep_flush_backlog_bitwise_and_journal_contiguous(tmp_path):
    """A 10-deep single-tenant backlog in one flush_period: per-row
    responses and final state bitwise equal to sequential handle(), and
    the write-ahead journal holds contiguous tick indices (the block
    rides ONE coalesced append_many)."""
    rng = np.random.default_rng(61)
    bat = _engine(str(tmp_path / "b"))
    seq = _engine(str(tmp_path / "s"))
    pan = _panel(seed=62)
    for e in (bat, seq):
        e.register("a", pan)
        e.register_shared("z", "a")
    rows = [rng.standard_normal(N) for _ in range(10)]

    seq_resps = [
        seq.handle({"kind": "tick", "tenant": "a", "x": r}) for r in rows
    ]
    seq_resps.append(
        seq.handle({"kind": "tick", "tenant": "z", "x": rows[0]})
    )
    for r in rows:
        bat.submit({"kind": "tick", "tenant": "a", "x": r})
    bat.submit({"kind": "tick", "tenant": "z", "x": rows[0]})
    bat_resps = bat.flush_period()

    assert all(r.ok for r in bat_resps)
    for rb, rs in zip(bat_resps, seq_resps):
        np.testing.assert_array_equal(
            np.asarray(rb.result.s), np.asarray(rs.result.s)
        )
    np.testing.assert_array_equal(
        np.asarray(bat._tenants["a"].state.s),
        np.asarray(seq._tenants["a"].state.s),
    )
    base, jrows = bat.store.journal("a").replay()
    ts = [t for t, _x, _m in jrows]
    assert ts == list(range(base, base + 10))  # contiguous block indices


# ---------------------------------------------------------------------------
# 5. crash_io kill matrix on the prefill replay path (chaos lane)
# ---------------------------------------------------------------------------


@pytest.mark.chaos_serving
def test_crash_io_kill_matrix_on_prefill_replay_path(tmp_path, monkeypatch):
    """Kill the engine at every i/o site while a DEEP (>= GEMM
    threshold) backlog flushes; every restart replays the journal
    through the prefill path.  Acked flush-1 ticks always survive, at
    most the in-flight flush-2 rows are additionally durable, and a
    second restart is bit-identical (the dual is deterministic)."""
    monkeypatch.setenv("DFM_PREFILL_MIN_K", "4")
    rng = np.random.default_rng(71)
    pan = _panel(seed=72)
    flush1 = [("a", rng.standard_normal(N)) for _ in range(2)]
    flush2 = [("a", rng.standard_normal(N)) for _ in range(8)]
    flush2.insert(3, ("b", rng.standard_normal(N)))

    site = 0
    crashes = 0
    while True:
        site += 1
        d = str(tmp_path / f"store{site}")
        eng = _engine(d)
        eng.register("a", pan)
        eng.register_shared("b", "a")
        for tid, row in flush1:
            eng.submit({"kind": "tick", "tenant": tid, "x": row})
        r1 = eng.flush_period()
        assert all(r.ok for r in r1)
        acked = {"a": 2, "b": 0}
        crashed = True
        ops0 = eng.store._io_ops
        with faults.inject(f"crash_io@{ops0 + site}"):
            try:
                for tid, row in flush2:
                    eng.submit({"kind": "tick", "tenant": tid, "x": row})
                eng.flush_period()
                crashed = False
            except faults.SimulatedCrash:
                crashes += 1
        if not crashed:
            break

        rec = _engine(d)
        rec2 = _engine(d)
        for tid in ("a", "b"):
            assert rec.resume(tid), f"site {site}: {tid} lost"
            assert rec2.resume(tid)
            got_t = int(rec._tenants[tid].state.t) - T
            extra = sum(1 for t2, _ in flush2 if t2 == tid)
            assert acked[tid] <= got_t <= acked[tid] + extra, (
                f"site {site}: tenant {tid} t={got_t}"
            )
            np.testing.assert_array_equal(
                np.asarray(rec._tenants[tid].state.s),
                np.asarray(rec2._tenants[tid].state.s),
            )
    assert crashes > 0


# ---------------------------------------------------------------------------
# 6. recover(prewarm): deep journals through the batched GEMM prefill
# ---------------------------------------------------------------------------


def test_recover_prewarm_deep_journal_rides_prefill(tmp_path, monkeypatch):
    monkeypatch.setenv("DFM_PREFILL_MIN_K", "4")
    d = str(tmp_path / "store")
    rng = np.random.default_rng(81)
    eng = _engine(d)
    eng.register("a", _panel(seed=82))
    for tid in ("b", "c"):
        eng.register_shared(tid, "a")
    rows = {tid: [rng.standard_normal(N) for _ in range(9)]
            for tid in ("a", "b", "c")}
    for i in range(9):
        for tid in ("a", "b", "c"):
            assert eng.handle(
                {"kind": "tick", "tenant": tid, "x": rows[tid][i]}
            ).ok
    live = {
        tid: (np.asarray(eng._tenants[tid].state.s).copy(),
              int(eng._tenants[tid].state.t))
        for tid in ("a", "b", "c")
    }

    telemetry.reset()
    rec = _engine(d)
    out = rec.recover(prewarm=3)
    assert out["prewarmed"] == 3
    assert telemetry._counters.get("serving.prefill.blocks", 0) >= 3
    for tid, (s, t) in live.items():
        ten = rec._tenants[tid]
        assert int(ten.state.t) == t
        assert _rel_err(ten.state.s, s) <= 1e-12

    # the same deep journals through the scalar prefill path (resume)
    # land on the SAME states the batched dispatch produced
    rec2 = _engine(d)
    for tid in ("a", "b", "c"):
        assert rec2.resume(tid)
        assert _rel_err(
            rec2._tenants[tid].state.s, np.asarray(rec._tenants[tid].state.s)
        ) <= 1e-12


def test_batched_prefill_dispatch_matches_scalar_and_pads(monkeypatch):
    """3 ragged lanes (bucket 4): lane-batched GEMM vs per-lane scalar
    prefill within dual parity; empty/deep lanes take their fallbacks."""
    monkeypatch.setenv("DFM_PREFILL_MIN_K", "1")
    model = _mk_model(d=3, seed=91)
    lanes = []
    for i, k in enumerate((8, 13, 16)):
        st = _state(model, t=9 + i, seed=92 + i)
        lanes.append((model, st, _mk_rows(model, k, seed=95 + i)))
    lanes.append((model, _state(model, t=4, seed=99), []))  # empty lane
    outs = batched_prefill_dispatch(lanes)
    for (m, st, rows), got in zip(lanes[:3], outs[:3]):
        ref = replay_ticks(m, st, rows)
        assert int(got.t) == int(ref.t)
        assert _rel_err(got.s, ref.s) <= 1e-12
    assert outs[3] is lanes[3][1]  # empty backlog: state passes through


# ---------------------------------------------------------------------------
# 7. summarize: prefill columns with "-" fallback
# ---------------------------------------------------------------------------


def test_summarize_prefill_columns_and_fallback(tmp_path):
    sink = str(tmp_path / "t.jsonl")
    h = telemetry.LatencyHistogram()
    for k in (8.0, 16.0, 16.0, 64.0):
        h.record(k)
    lines = [
        {"run_id": "s1", "entry": "serving", "time_unix": 3.0,
         "wall_s": 0.01, "kind": "tick", "outcome": "ok"},
        {"entry": "hist", "time_unix": 3.5,
         "name": "serving.prefill.depth", "labels": {"unit": "ticks"},
         "hist": h.to_dict()},
        {"entry": "metrics", "time_unix": 4.0,
         "counters": {"serving.prefill.blocks": 7,
                      "serving.prefill.ticks": 104.0},
         "gauges": {"serving.occupancy.prefill_s": 0.3,
                    "serving.occupancy.dispatch_s": 0.7}},
    ]
    with open(sink, "w") as f:
        for ln in lines:
            f.write(json.dumps(ln) + "\n")
    out = telemetry.summarize(sink)
    assert "pf_blk" in out and "pf_k50" in out
    srow = [l for l in out.splitlines() if l.startswith("serving")]
    assert srow and " 7 " in srow[0] and " 16 " in srow[0]
    # prefill shows up in the occupancy split: a/d/p/j/c/e = 0/70/30/...
    assert "0/70/30/0/0/0" in srow[0]

    # pre-PR-20 sink: no prefill counters -> "-" columns, no crash
    sink2 = str(tmp_path / "old.jsonl")
    with open(sink2, "w") as f:
        f.write(json.dumps(lines[0]) + "\n")
        f.write(json.dumps(
            {"entry": "metrics", "time_unix": 4.0, "counters": {},
             "gauges": {}}
        ) + "\n")
    out2 = telemetry.summarize(sink2)
    srow2 = [l for l in out2.splitlines() if l.startswith("serving")]
    assert srow2
    # the prefill depth hist stays out of the latency columns
    assert "fault_in" not in out2 or True


# ---------------------------------------------------------------------------
# 8. AOT plan registration from the spec
# ---------------------------------------------------------------------------


def test_prefill_depth_registers_bucketed_aot_plans():
    spec = CompileSpec(
        T=64, N=16, r=2, p=2, kernels=(),
        serving_period=3, prefill_depth=64,
    )
    plans = _kernel_plan(spec)
    for Kb in (1, 2, 4, 8, 16, 32, 64):
        assert f"serving_prefill@K{Kb}" in plans
        assert f"serving_tick_block@K{Kb}" in plans
    assert "serving_prefill@K128" not in plans
    # plan avals: (model, state, X(Kb,N), mask, k) — depth is traced
    fn, lower_args, _kw, statics, _mk = plans["serving_prefill@K64"]
    assert statics == ()
    assert lower_args[2].shape[0] == 64

    # prefill plans require the serving avals
    spec_off = CompileSpec(T=64, N=16, kernels=(), prefill_depth=64)
    assert not any("prefill" in k for k in _kernel_plan(spec_off))
