"""Observed-factor (nfac_o > 0) estimation — the FAVAR-style capability the
reference declares (DFMModel.nfac_o, dfm_functions.ipynb cells 6-7) but never
implements; semantics: observed factors enter every loading regression, the
F-step solves only the unobserved block on the residual."""

import numpy as np
import pytest

from dynamic_factor_models_tpu.models.dfm import DFMConfig, estimate_dfm, estimate_factor


def _dgp(T=180, N=40, seed=0):
    rng = np.random.default_rng(seed)
    fo = rng.standard_normal((T, 1))
    fu = rng.standard_normal((T, 1))
    lam_o = rng.standard_normal((N, 1))
    lam_u = rng.standard_normal((N, 1))
    x = fo @ lam_o.T + fu @ lam_u.T + 0.1 * rng.standard_normal((T, N))
    return x, fo, fu


def test_observed_factor_recovers_unobserved_space():
    x, fo, fu = _dgp()
    cfg = DFMConfig(nfac_o=1, nfac_u=1, n_factorlag=1, n_uarlag=1, tol=1e-10)
    res = estimate_dfm(
        x, np.ones(x.shape[1]), 0, x.shape[0] - 1, cfg, observed_factor=fo
    )
    F = np.asarray(res.factor)
    assert F.shape[1] == 2
    # column 0 is the observed factor passed through verbatim
    np.testing.assert_allclose(F[:, 0], fo[:, 0], atol=1e-12)
    # the estimated unobserved factor spans fu (up to sign/scale):
    # residualize both on fo first since standardization mixes in a constant
    corr = np.corrcoef(F[:, 1], fu[:, 0])[0, 1]
    assert abs(corr) > 0.95, f"unobserved factor poorly recovered: corr={corr}"


def test_observed_factor_improves_fit():
    x, fo, _ = _dgp(seed=1)
    incl = np.ones(x.shape[1])
    base = DFMConfig(nfac_u=1, n_factorlag=1, n_uarlag=1)
    with_o = DFMConfig(nfac_o=1, nfac_u=1, n_factorlag=1, n_uarlag=1)
    _, fes_u = estimate_factor(x, incl, 0, x.shape[0] - 1, base)
    _, fes_o = estimate_factor(
        x, incl, 0, x.shape[0] - 1, with_o, observed_factor=fo
    )
    # adding a true observed factor must explain strictly more variance than
    # a single unobserved factor alone
    assert float(fes_o.ssr) < float(fes_u.ssr)


def test_observed_factor_validation():
    x, fo, _ = _dgp()
    cfg = DFMConfig(nfac_o=1, nfac_u=1)
    with pytest.raises(ValueError, match="requires observed_factor"):
        estimate_factor(x, np.ones(x.shape[1]), 0, x.shape[0] - 1, cfg)
    with pytest.raises(ValueError, match="columns"):
        estimate_factor(
            x, np.ones(x.shape[1]), 0, x.shape[0] - 1, cfg,
            observed_factor=np.hstack([fo, fo]),
        )
    fo_nan = fo.copy()
    fo_nan[5, 0] = np.nan
    with pytest.raises(ValueError, match="NaN-free"):
        estimate_factor(
            x, np.ones(x.shape[1]), 0, x.shape[0] - 1, cfg, observed_factor=fo_nan
        )


def test_observed_factor_shape_validation():
    x, fo, _ = _dgp()
    cfg = DFMConfig(nfac_o=1, nfac_u=1)
    with pytest.raises(ValueError, match="2-D"):
        estimate_factor(
            x, np.ones(x.shape[1]), 0, x.shape[0] - 1, cfg,
            observed_factor=fo[:, 0],  # 1-D slice: clear error, not IndexError
        )
    with pytest.raises(ValueError, match="full-length"):
        estimate_factor(
            x, np.ones(x.shape[1]), 5, x.shape[0] - 1, cfg,
            observed_factor=fo[5:],  # window-length instead of full-length
        )
