"""Large-N collapse: N-free per-step filtering, CPU-sized tier-1 lane.

The ISSUE-10 contract is that per-step filter cost is independent of the
cross-section width N everywhere in the estimation stack.  These tests pin
the two properties that make that true, at sizes a CPU test runner can
afford (the 10k-100k scaling numbers live in `bench.py --large-n` /
docs/BENCH_large_n.json):

* HLO pins — the scan bodies (stablehlo.while regions) of the collapsed
  kernels carry NO N-sized operand.  N = 1999 (prime, and not a bucket
  size) so a shape leak cannot hide behind a coincidental constant; the
  match is on shape tokens ([<x]1999x), not the bare digits, so float
  literals like 1.999e0 cannot false-positive.
* Memory-regression guard — the compiled collapsed-AR EM step's total
  footprint at N = 2048 stays O(T N): the dense-path state (r p + N)^2
  covariance scan at this shape would need ~13 GB of scan stacks, the
  collapsed step measures ~tens of MB, and the 1 GB assert sits two
  orders of magnitude above the measurement but three below the
  regression.
* N ~ 2k smoke — the collapsed EM step, fan, news, and simulation
  smoother all auto-dispatch (N > LARGE_N_THRESHOLD) and produce finite
  output at a width above every dispatch threshold.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models import ssm_ar as M
from dynamic_factor_models_tpu.models.ssm import LARGE_N_THRESHOLD, SSMParams

pytestmark = pytest.mark.large_n

N_PIN = 1999  # prime, not a bucket size: shape leaks cannot hide
_SHAPE_TOKEN = re.compile(r"[<x]%dx" % N_PIN)


def _while_bodies(hlo: str):
    """Extract every stablehlo.while op's full region text by brace
    matching from each occurrence to its closing brace."""
    bodies = []
    start = 0
    while True:
        i = hlo.find("stablehlo.while", start)
        if i < 0:
            break
        j = hlo.find("{", i)
        depth, k = 1, j + 1
        while depth and k < len(hlo):
            if hlo[k] == "{":
                depth += 1
            elif hlo[k] == "}":
                depth -= 1
            k += 1
        bodies.append(hlo[i:k])
        start = k
    return bodies


def _ragged_panel(T, N, r=2, seed=5, dtype=np.float32):
    rng = np.random.default_rng(seed)
    f = np.zeros((T, r))
    for t in range(1, T):
        f[t] = 0.6 * f[t - 1] + rng.standard_normal(r)
    lam = 0.5 * rng.standard_normal((N, r))
    x = f @ lam.T + rng.standard_normal((T, N))
    heads = rng.integers(0, T // 6, N)
    tails = rng.integers(0, T // 6, N)
    for i in range(N):
        x[: heads[i], i] = np.nan
        if tails[i]:
            x[T - tails[i]:, i] = np.nan
    return x.astype(dtype)


def _qd_setup(T, N, r=2, dtype=np.float32):
    from dynamic_factor_models_tpu.ops.masking import fillz, mask_of

    x = jnp.asarray(_ragged_panel(T, N, r, dtype=dtype))
    xz, m = fillz(x), mask_of(x)
    qd = M.compute_qd_stats(xz, m)
    rng = np.random.default_rng(0)
    params = M.SSMARParams(
        lam=jnp.asarray(0.3 * rng.standard_normal((N, r)), xz.dtype),
        phi=jnp.zeros(N, xz.dtype),
        sigv2=jnp.ones(N, xz.dtype),
        A=0.5 * jnp.eye(r, dtype=xz.dtype)[None],
        Q=jnp.eye(r, dtype=xz.dtype),
    )
    return params, xz, m, qd


def test_qd_em_scan_bodies_are_n_free():
    """No scan body of the collapsed-AR EM step carries an N-sized
    operand: all O(N) work (collapse GEMMs, M-step Grams) lowers OUTSIDE
    the whiles, so per-step filter cost cannot depend on N."""
    params, xz, _, qd = _qd_setup(64, N_PIN)
    hlo = M.em_step_ar_qd.lower(params, xz, qd).as_text()
    bodies = _while_bodies(hlo)
    assert bodies, "no while loops found — scan lowering changed?"
    for body in bodies:
        leak = _SHAPE_TOKEN.search(body)
        assert leak is None, (
            f"N-sized operand inside a scan body: ...{body[max(0, leak.start() - 120):leak.start() + 60]}..."
        )


def test_collapsed_fan_scan_bodies_are_n_free():
    from dynamic_factor_models_tpu.scenarios import fanout

    rng = np.random.default_rng(2)
    params = SSMParams(
        lam=jnp.asarray(0.3 * rng.standard_normal((N_PIN, 2)), jnp.float32),
        R=jnp.ones(N_PIN, jnp.float32),
        A=0.5 * jnp.eye(2, dtype=jnp.float32)[None],
        Q=jnp.eye(2, dtype=jnp.float32),
    )
    x = jnp.asarray(_ragged_panel(24, N_PIN))
    stats = fanout._collapse_fan_stats(params, x, 4, None)
    hlo = fanout._conditional_fan_collapsed_impl.lower(
        params, *stats, horizon=4, observables=True
    ).as_text()
    bodies = _while_bodies(hlo)
    assert bodies
    for body in bodies:
        assert _SHAPE_TOKEN.search(body) is None, (
            "N-sized operand inside a collapsed-fan scan body"
        )


def test_qd_em_step_memory_stays_collapsed():
    """Compiled-footprint regression guard: the collapsed-AR EM step at
    (T, N) = (128, 2048) f32 must stay O(T N) — a reintroduced dense
    (r p + N)-state scan would need gigabytes of (T, k, k) stacks."""
    params, xz, _, qd = _qd_setup(128, 2048)
    ex = jax.jit(M.em_step_ar_qd).lower(params, xz, qd).compile()
    ma = ex.memory_analysis()
    if ma is None:
        pytest.skip("backend reports no memory analysis")
    total = (
        getattr(ma, "temp_size_in_bytes", 0)
        + getattr(ma, "argument_size_in_bytes", 0)
        + getattr(ma, "output_size_in_bytes", 0)
    )
    assert 0 < total < 1_000_000_000, (
        f"collapsed-AR EM step footprint {total / 1e6:.0f} MB at "
        f"(128, 2048) — dense-state scan stacks have crept back in"
    )


def test_dense_budget_estimate_vs_collapsed_footprint():
    """The guard that routes users to method='collapsed' is calibrated:
    the dense estimate at (128, 2048) exceeds the measured collapsed
    footprint by >= 100x."""
    dense = M._dense_ar_mem_bytes(128, 2048, 2, 1, 4)
    assert dense > 100 * 100e6  # ~10 GB vs the ~tens-of-MB collapsed step


def test_large_n_smoke_em_and_nowcast():
    """N = 2048 (> LARGE_N_THRESHOLD) collapsed EM: two steps, finite and
    improving; the O(T N) idio recovery returns a full panel."""
    assert 2048 > LARGE_N_THRESHOLD
    params, xz, m, qd = _qd_setup(96, 2048)
    p1, ll1 = M.em_step_ar_qd(params, xz, qd)
    p2, ll2 = M.em_step_ar_qd(p1, xz, qd)
    assert np.isfinite(float(ll1)) and np.isfinite(float(ll2))
    assert float(ll2) >= float(ll1) - 1e-6 * abs(float(ll1))
    pg = M._guard_params_qd(p2)
    mm, cc, pm, pc, _ = M._filter_ar_qd(pg, xz, qd)
    Tmq, _ = M._qd_companion(pg)
    s_sm, _, _ = M._rts_scan(Tmq, mm, cc, pm, pc)
    idio = M.idio_moments_qd(pg, xz, qd, s_sm)
    assert idio.shape == xz.shape and np.isfinite(np.asarray(idio)).all()


def test_large_n_smoke_fan_news_simsmoother():
    """The scenario fan, news decomposition, and simulation smoother all
    auto-route through the collapsed paths at N = 2048 and return finite,
    correctly-shaped results."""
    from dynamic_factor_models_tpu.models import bayes, news
    from dynamic_factor_models_tpu.scenarios import fanout

    T, N, r, h, S = 48, 2048, 2, 4, 3
    x = _ragged_panel(T, N, r, seed=9).astype(np.float64)
    rng = np.random.default_rng(1)
    params = SSMParams(
        lam=jnp.asarray(0.3 * rng.standard_normal((N, r))),
        R=jnp.ones(N),
        A=0.5 * jnp.eye(r)[None],
        Q=jnp.eye(r),
    )
    cond = np.full((S, h, N), np.nan)
    cond[:, 0, 0] = np.linspace(-1, 1, S)
    f, Pf = fanout.conditional_fan(params, x, h, cond, observables=False)
    assert f.shape == (S, h, r) and np.isfinite(np.asarray(f)).all()
    fd, ll = fanout.draw_fan(
        params, x, h, 2, cond, seed=0, observables=False
    )
    assert fd.shape == (S, 2, h, r) and np.isfinite(np.asarray(ll)).all()

    draw, ll1 = bayes.simulation_smoother(params, x, seed=0)
    assert draw.shape == (T, r) and np.isfinite(float(ll1))

    x_new = x.copy()
    tgt = (T - 1, 0)
    x_new[tgt] = np.nan
    x_old = x_new.copy()
    rel_i = np.where(~np.isnan(x_new[T - 2]))[0][:3]
    x_old[T - 2, rel_i] = np.nan
    res = news.nowcast_news(params, x_old, x_new, tgt)
    assert np.isfinite(res.total_revision)
    assert np.isfinite(np.asarray(res.news)).all()
    np.testing.assert_allclose(
        float(res.nowcast_path[-1] - res.nowcast_path[0]),
        res.total_revision, rtol=1e-10, atol=1e-12,
    )
