"""Observability layer (utils/telemetry.py): RunRecord field completeness
for every estimation entry point, JSONL round-trip + line atomicity,
compile-counter delta correctness, heartbeat parity, the disabled-path
singleton, the summarize CLI, and the satellite fixes that rode along
(zero-iteration trace contract, iters_per_sec guard, checkpoint temp-file
hygiene)."""

import json
import os
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.utils import telemetry as T

pytestmark = pytest.mark.telemetry

# every RunRecord must carry these regardless of entry point (ISSUE
# acceptance bar); entry points add shapes/bucket/n_iter detail on top
REQUIRED_FIELDS = {
    "entry", "run_id", "time_unix", "wall_s", "platform", "device_kind",
    "n_devices", "x64", "donate", "shapes", "n_iter", "converged",
    "phase_s", "counters_delta", "persistent_cache_delta", "memory",
}


@pytest.fixture
def sink(tmp_path, monkeypatch):
    """Point DFM_TELEMETRY at a fresh JSONL file and clear the registry."""
    path = str(tmp_path / "runs.jsonl")
    monkeypatch.setenv("DFM_TELEMETRY", path)
    monkeypatch.delenv("DFM_PROFILE_DIR", raising=False)
    monkeypatch.setattr(T, "_explicit_enabled", None)
    monkeypatch.setattr(T, "_explicit_sink", None)
    T.reset()
    return path


def _recs(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _by_entry(path, entry):
    out = [r for r in _recs(path) if r["entry"] == entry]
    assert out, f"no record for entry {entry!r}"
    return out


def _assert_complete(rec):
    missing = REQUIRED_FIELDS - set(rec)
    assert not missing, f"record {rec['entry']} missing fields: {missing}"
    assert rec["wall_s"] > 0
    assert isinstance(rec["phase_s"], dict)
    assert isinstance(rec["counters_delta"], dict)
    assert rec["memory"].get("source") in (
        "memory_stats", "live_buffers", "unavailable"
    )


# ---------------------------------------------------------------------------
# per-entry-point field completeness
# ---------------------------------------------------------------------------


def test_estimate_dfm_em_record(sink, rng):
    from dynamic_factor_models_tpu.models.ssm import DFMConfig, estimate_dfm_em

    y = rng.standard_normal((48, 10))
    estimate_dfm_em(y, np.ones(10), 0, 47,
                    DFMConfig(nfac_u=2, n_factorlag=1), max_em_iter=3)
    (rec,) = _by_entry(sink, "estimate_dfm_em")
    _assert_complete(rec)
    assert rec["shapes"] == {"T": 48, "N": 10, "r": 2, "p": 1}
    assert rec["n_iter"] == 3 and rec["converged"] is False
    assert isinstance(rec["final_loglik"], float)
    assert rec["phase_s"], "outer record should aggregate phase spans"
    # the inner EM loop leaves its own child record linked to the outer one
    (child,) = _by_entry(sink, "run_em_loop")
    assert child["parent"] == rec["run_id"]
    assert child["n_iter"] == 3
    assert child["config"]["checkpointed"] is False


def test_estimate_dfm_em_ar_record(sink, rng):
    from dynamic_factor_models_tpu.models.ssm_ar import (
        DFMConfig, estimate_dfm_em_ar,
    )

    y = rng.standard_normal((40, 8))
    estimate_dfm_em_ar(y, np.ones(8), 0, 39,
                       DFMConfig(nfac_u=1, n_factorlag=1), max_em_iter=3)
    (rec,) = _by_entry(sink, "estimate_dfm_em_ar")
    _assert_complete(rec)
    assert rec["shapes"]["T"] == 40 and rec["shapes"]["N"] == 8
    assert rec["n_iter"] == 3


def test_estimate_mixed_freq_record(sink, rng):
    from dynamic_factor_models_tpu.models.mixed_freq import (
        estimate_mixed_freq_dfm,
    )

    T_, N = 48, 7
    x = rng.standard_normal((T_, N))
    x[np.arange(T_) % 3 != 2, N - 2:] = np.nan  # quarterly tail
    is_q = np.zeros(N, bool)
    is_q[N - 2:] = True
    estimate_mixed_freq_dfm(x, is_q, r=1, p=5, max_em_iter=3)
    (rec,) = _by_entry(sink, "estimate_mixed_freq_dfm")
    _assert_complete(rec)
    assert rec["shapes"]["n_quarterly"] == 2
    assert rec["n_iter"] == 3


def test_estimate_factor_record(sink, rng):
    from dynamic_factor_models_tpu.models.dfm import DFMConfig, estimate_factor

    y = rng.standard_normal((48, 10))
    estimate_factor(y, np.ones(10), 0, 47, DFMConfig(nfac_u=2))
    (rec,) = _by_entry(sink, "estimate_factor")
    _assert_complete(rec)
    assert rec["shapes"] == {"T": 48, "N": 10, "r": 2}
    assert rec["n_iter"] >= 1
    assert isinstance(rec["ssr"], float)
    assert "als_core" in rec["phase_s"]


def test_fit_ms_dfm_record(sink, rng):
    from dynamic_factor_models_tpu.models.msdfm import fit_ms_dfm

    x = rng.standard_normal((60, 5))
    fit_ms_dfm(x, n_steps=30, n_restarts=2, seed=0)
    (rec,) = _by_entry(sink, "fit_ms_dfm")
    _assert_complete(rec)
    assert rec["shapes"]["T"] == 60 and rec["shapes"]["N"] == 5
    assert rec["n_iter"] == 30
    assert rec["n_finite_restarts"] >= 1


def test_bootstrap_records(sink, rng):
    from dynamic_factor_models_tpu.models.favar import wild_bootstrap_irfs

    y = rng.standard_normal((60, 3)) * 0.3
    wild_bootstrap_irfs(y, 1, 0, 59, horizon=4, n_reps=8, seed=0)
    (rec,) = _by_entry(sink, "bootstrap_irfs")
    _assert_complete(rec)
    assert rec["config"]["resample"]  # scheme name captured
    assert rec["shapes"]["n_reps"] == 8 and rec["n_iter"] == 8
    assert 0.0 <= rec["finite_fraction"] <= 1.0


def test_bootstrap_resumable_record_and_tmp_hygiene(sink, tmp_path, rng):
    from dynamic_factor_models_tpu.models.favar import (
        wild_bootstrap_irfs_resumable,
    )

    y = rng.standard_normal((60, 3)) * 0.3
    ck = str(tmp_path / "boot.npz")
    wild_bootstrap_irfs_resumable(y, 1, 0, 59, ck, horizon=4,
                                  n_reps=8, chunk_reps=4, seed=0)
    (rec,) = _by_entry(sink, "wild_bootstrap_irfs_resumable")
    _assert_complete(rec)
    assert rec["n_chunks"] == 2 and rec["start_chunk"] == 0
    # atomic rename left the final checkpoint and zero temp files behind
    leftovers = [p for p in os.listdir(tmp_path) if ".tmp." in p]
    assert not leftovers, leftovers
    assert os.path.exists(ck)


# ---------------------------------------------------------------------------
# sink mechanics
# ---------------------------------------------------------------------------


def test_jsonl_roundtrip_and_line_atomicity(sink):
    """Concurrent writers through the single-append path produce exactly
    n_threads * n_each parseable lines — whole lines, never fragments."""
    n_threads, n_each = 8, 25

    def work(i):
        for j in range(n_each):
            with T.run_record("thread_entry", config={"i": i, "j": j}):
                pass

    threads = [threading.Thread(target=work, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    recs = _recs(sink)  # json.loads raises on any torn line
    assert len(recs) == n_threads * n_each
    seen = {(r["config"]["i"], r["config"]["j"]) for r in recs}
    assert len(seen) == n_threads * n_each
    run_ids = {r["run_id"] for r in recs}
    assert len(run_ids) == n_threads * n_each


def test_counter_deltas_bracket_the_record(sink, rng):
    """counters_delta is the per-kernel difference across the record's
    lifetime: a second identical run reuses compiled programs, so its
    record shows runs but no fresh compiles for the EM kernels."""
    from dynamic_factor_models_tpu.models.ssm import DFMConfig, estimate_dfm_em
    from dynamic_factor_models_tpu.utils.compile import counters

    y = rng.standard_normal((48, 10))
    cfg = DFMConfig(nfac_u=2, n_factorlag=1)
    c0 = counters()
    estimate_dfm_em(y, np.ones(10), 0, 47, cfg, max_em_iter=3)
    estimate_dfm_em(y, np.ones(10), 0, 47, cfg, max_em_iter=3)
    first, second = _by_entry(sink, "estimate_dfm_em")
    # the record deltas, summed, reproduce the registry-level difference
    c1 = counters()
    for kernel, d in first["counters_delta"].items():
        for field, v in d.items():
            total = c1.get(kernel, {}).get(field, 0) - c0.get(kernel, {}).get(field, 0)
            assert total >= v - 1e-9, (kernel, field)
    # production default dispatches the health-guarded while-loop kernel
    em1 = first["counters_delta"].get("em_loop_guarded", {})
    em2 = second["counters_delta"].get("em_loop_guarded", {})
    assert em1.get("runs", 0) >= 1
    assert em2.get("runs", 0) >= 1
    assert em2.get("compiles", 0) == 0, (
        "second identical run must not recompile the EM loop"
    )


def test_heartbeat_parity_and_counter(sink, monkeypatch, rng):
    """DFM_HEARTBEAT=k compiles a different (callback-bearing) loop with
    IDENTICAL numerics, and the callback lands in the registry."""
    from dynamic_factor_models_tpu.models.ssm import DFMConfig, estimate_dfm_em

    y = rng.standard_normal((48, 10))
    cfg = DFMConfig(nfac_u=2, n_factorlag=1)
    base = estimate_dfm_em(y, np.ones(10), 0, 47, cfg, max_em_iter=6)
    T.reset()
    monkeypatch.setenv("DFM_HEARTBEAT", "2")
    hb = estimate_dfm_em(y, np.ones(10), 0, 47, cfg, max_em_iter=6)
    np.testing.assert_array_equal(
        np.asarray(hb.loglik_path), np.asarray(base.loglik_path)
    )
    snap = T.snapshot()
    assert snap["counters"].get("em_heartbeat_events", 0) >= 1
    assert "em_heartbeat_loglik" in snap["gauges"]
    child = _by_entry(sink, "run_em_loop")[-1]
    assert child["heartbeat_every"] == 2


def test_disabled_path_returns_singleton(monkeypatch):
    monkeypatch.delenv("DFM_TELEMETRY", raising=False)
    monkeypatch.delenv("DFM_PROFILE_DIR", raising=False)
    monkeypatch.setattr(T, "_explicit_enabled", None)
    monkeypatch.setattr(T, "_explicit_sink", None)
    assert not T.enabled()
    a = T.run_record("anything", config={"x": 1})
    assert a is T.run_record("other")
    assert a.active is False
    with a as rec:
        rec.set(n_iter=1).add_phase("p", 0.1)


def test_explicit_enable_without_env(tmp_path, monkeypatch):
    monkeypatch.delenv("DFM_TELEMETRY", raising=False)
    monkeypatch.setattr(T, "_explicit_enabled", None)
    monkeypatch.setattr(T, "_explicit_sink", None)
    path = str(tmp_path / "explicit.jsonl")
    T.enable(sink=path)
    try:
        assert T.enabled() and T.sink_path() == path
        with T.run_record("explicit_entry"):
            pass
        assert _by_entry(path, "explicit_entry")
    finally:
        T.disable()
        monkeypatch.setattr(T, "_explicit_enabled", None)


def test_broken_sink_never_raises(monkeypatch):
    monkeypatch.setenv("DFM_TELEMETRY", "/proc/definitely/not/writable.jsonl")
    with T.run_record("doomed_sink"):
        pass  # OSError on the append is swallowed; estimation must survive
    assert T.records()[-1]["entry"] == "doomed_sink"


def test_record_error_field(sink):
    with pytest.raises(RuntimeError, match="boom"):
        with T.run_record("exploding"):
            raise RuntimeError("boom")
    (rec,) = _by_entry(sink, "exploding")
    assert rec["error"] == "RuntimeError: boom"
    assert rec["wall_s"] >= 0


# ---------------------------------------------------------------------------
# summarize CLI
# ---------------------------------------------------------------------------


def test_summarize_cli(sink, rng, capsys):
    from dynamic_factor_models_tpu.models.dfm import DFMConfig, estimate_factor

    y = rng.standard_normal((48, 10))
    estimate_factor(y, np.ones(10), 0, 47, DFMConfig(nfac_u=2))
    assert T.main(["summarize", sink]) == 0
    out = capsys.readouterr().out
    assert "estimate_factor" in out and "aggregate by entry" in out
    assert "48x10,r=2" in out
    # --json mode round-trips
    assert T.main(["summarize", sink, "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed and parsed[0]["entry"] == "estimate_factor"
    # --entry filter, and a clean exit on a missing file
    assert T.main(["summarize", sink, "--entry", "nope"]) == 0
    assert "no records" in capsys.readouterr().out
    assert T.main(["summarize", str(sink) + ".missing"]) == 1


def test_module_cli_shim():
    """`python -m dynamic_factor_models_tpu.telemetry` resolves to the same
    implementation (the package-level shim re-exports utils.telemetry)."""
    from dynamic_factor_models_tpu import telemetry as shim

    assert shim.main is T.main
    assert shim.run_record is T.run_record


# ---------------------------------------------------------------------------
# satellites: zero-iteration trace, iters_per_sec guard, checkpoint hygiene
# ---------------------------------------------------------------------------


def test_zero_iter_collect_path_returns_empty_trace(rng):
    from dynamic_factor_models_tpu.models.emloop import run_em_loop
    from dynamic_factor_models_tpu.models.ssm import SSMParams, em_step
    from dynamic_factor_models_tpu.utils.profiling import ConvergenceTrace

    xz = jnp.asarray(rng.standard_normal((20, 4)))
    m = jnp.ones((20, 4), bool)
    params = SSMParams(lam=jnp.ones((4, 1)), R=jnp.ones(4),
                       A=0.4 * jnp.eye(1)[None], Q=jnp.eye(1))
    p_out, llpath, n_iter, trace = run_em_loop(
        em_step, params, (xz, m), 1e-8, 0, collect_path=True
    )
    assert n_iter == 0 and llpath.size == 0
    assert isinstance(trace, ConvergenceTrace)
    assert trace.values == [] and np.isnan(trace.iters_per_sec)
    # and without collect_path the trace stays None, params untouched
    _, _, n2, tr2 = run_em_loop(em_step, params, (xz, m), 1e-8, 0)
    assert n2 == 0 and tr2 is None


def test_iters_per_sec_zero_dt_is_nan():
    from dynamic_factor_models_tpu.utils.profiling import ConvergenceTrace

    tr = ConvergenceTrace("t")
    assert np.isnan(tr.iters_per_sec)  # no iterations at all
    tr.times = [5.0]
    tr.values = [-1.0]
    assert np.isnan(tr.iters_per_sec)  # single sample: zero elapsed
    tr.times = [5.0, 5.0]
    tr.values = [-1.0, -0.5]
    assert np.isnan(tr.iters_per_sec)  # clock didn't advance


def test_checkpoint_failed_save_cleans_temp(tmp_path, monkeypatch, rng):
    """A save_pytree failure mid-run must propagate AND leave no temp file
    next to the checkpoint path."""
    from dynamic_factor_models_tpu.models.emloop import run_em_loop
    from dynamic_factor_models_tpu.models.ssm import SSMParams, em_step
    from dynamic_factor_models_tpu.utils import checkpoint as ck_mod

    xz = jnp.asarray(rng.standard_normal((30, 5)))
    m = jnp.ones((30, 5), bool)
    params = SSMParams(
        lam=jnp.asarray(rng.standard_normal((5, 2)) * 0.5),
        R=jnp.ones(5), A=0.4 * jnp.eye(2)[None], Q=jnp.eye(2),
    )
    real_save = ck_mod.save_pytree

    def failing_save(path, tree):
        real_save(path, tree)  # the temp file exists on disk...
        raise OSError("disk full")  # ...when the failure hits

    monkeypatch.setattr(ck_mod, "save_pytree", failing_save)
    ck = str(tmp_path / "em.npz")
    with pytest.raises(OSError, match="disk full"):
        run_em_loop(em_step, params, (xz, m), 1e-10, 20,
                    checkpoint_path=ck, checkpoint_every=5)
    leftovers = [p for p in os.listdir(tmp_path) if ".tmp." in p]
    assert not leftovers, leftovers
    assert not os.path.exists(ck)


def test_checkpoint_temp_names_are_unique(tmp_path, rng):
    """Two runs against the same checkpoint path generate distinct temp
    names (pid+uuid suffix), so neither can clobber the other's
    half-written archive."""
    from dynamic_factor_models_tpu.models.emloop import run_em_loop
    from dynamic_factor_models_tpu.models.ssm import SSMParams, em_step
    from dynamic_factor_models_tpu.utils import checkpoint as ck_mod

    xz = jnp.asarray(rng.standard_normal((30, 5)))
    m = jnp.ones((30, 5), bool)
    params = SSMParams(
        lam=jnp.asarray(rng.standard_normal((5, 2)) * 0.5),
        R=jnp.ones(5), A=0.4 * jnp.eye(2)[None], Q=jnp.eye(2),
    )
    seen = []
    real_save = ck_mod.save_pytree

    def spying_save(path, tree):
        seen.append(os.path.basename(path))
        return real_save(path, tree)

    import unittest.mock as mock

    with mock.patch.object(ck_mod, "save_pytree", spying_save):
        ck = str(tmp_path / "em.npz")
        run_em_loop(em_step, params, (xz, m), 1e-10, 10,
                    checkpoint_path=ck, checkpoint_every=3)
        os.remove(ck)
        run_em_loop(em_step, params, (xz, m), 1e-10, 10,
                    checkpoint_path=ck, checkpoint_every=3)
    assert len(seen) >= 2
    assert len(set(seen)) == len(seen), f"temp names collided: {seen}"
    assert all(".tmp." in s and s.endswith(".npz") for s in seen)
