"""Composable EM transform stacks (models/transforms.py).

Pinned claims:

1. `resolve` maps every stack that reproduces a pre-stack variant to the
   LITERAL module-level jitted step object the hand-written call sites
   dispatched — identity (`is`), not equivalence — so the PR 1-4/8 HLO
   byte-identity pins keep holding by construction; the previously
   unreachable PRODUCTS resolve to models/emcore.py;
2. invalid stacks (unknown core/kind, duplicate axes, products no core
   supports) fail loudly at resolve time, not at trace time;
3. the composed steps are exact: `em_step_collapsed` tracks
   `em_step_stats` at 1e-10 per iteration, and the public AR entry point
   with steady=True / n_shards=8 / both matches the plain collapsed fit
   at 1e-10 (observed ~1e-13) — the speed axes change the schedule, not
   the numbers;
4. AR series padding (emcore.pad_ar_params + zero data + all-False mask)
   is exactly inert — the exactness the shard transform's N-padding
   relies on;
5. the AOT plan is DERIVED: `enumerate_stacks` on a maximal spec yields
   exactly the frozen pre-stack kernel key set (no orphans, no
   duplicates), composed kernels appear only by opt-in, and a composed
   kernel precompiles once then serves warm AOT hits.
"""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamic_factor_models_tpu.models import emcore
from dynamic_factor_models_tpu.models import mixed_freq
from dynamic_factor_models_tpu.models import ssm
from dynamic_factor_models_tpu.models import ssm_ar
from dynamic_factor_models_tpu.models import transforms as tfm
from dynamic_factor_models_tpu.models.dfm import DFMConfig
from dynamic_factor_models_tpu.utils import compile as cc
from dynamic_factor_models_tpu.utils import telemetry as T


@pytest.fixture(autouse=True)
def _clean_compile_env(monkeypatch):
    for var in ("DFM_SHAPE_BUCKETS", "DFM_T_BUCKETS", "DFM_N_BUCKETS",
                "DFM_REP_BUCKET"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("DFM_DONATE", "0")


# ---------------------------------------------------------------------------
# 1. resolution identity: stacks map to the hand-written step OBJECTS
# ---------------------------------------------------------------------------


def test_resolve_returns_literal_hand_written_steps():
    assert tfm.resolve(tfm.Stack("ssm")).step is ssm.em_step_stats
    assert (
        tfm.resolve(tfm.Stack("ssm", (tfm.steady_tail(16),))).step
        is ssm._steady_step_for(16, 0)
    )
    assert tfm.resolve(tfm.Stack("ssm.legacy")).step is ssm.em_step
    assert tfm.resolve(tfm.Stack("ssm.assoc")).step is ssm.em_step_assoc
    assert tfm.resolve(tfm.Stack("ssm.sqrt")).step is ssm.em_step_sqrt
    assert (
        tfm.resolve(tfm.Stack("ssm.sqrt_collapsed")).step
        is ssm.em_step_sqrt_collapsed
    )
    assert tfm.resolve(tfm.Stack("ar")).step is ssm_ar.em_step_ar
    assert (
        tfm.resolve(tfm.Stack("ar", (tfm.collapse(),))).step
        is ssm_ar.em_step_ar_qd
    )
    assert tfm.resolve(tfm.Stack("mf")).step is mixed_freq.em_step_mf_stats


def test_resolve_records_loop_policy_and_fallbacks():
    res = tfm.resolve(
        tfm.Stack(
            "ar",
            (tfm.collapse(), tfm.steady_tail(32), tfm.guard(),
             tfm.batch(4), tfm.donate()),
        )
    )
    assert res.step is emcore._ar_steady_step_for(32, 0)
    assert res.carry == "ar_steady" and res.arg_kind == "qd_tail"
    assert res.t_star == 32 and res.batch == 4
    assert res.guard is True and res.donate is True
    # the guard ladder's demote rung: the exact plain collapsed step
    assert res.fallback_step is ssm_ar.em_step_ar_qd


def test_resolve_composed_products_live_in_emcore():
    assert (
        tfm.resolve(tfm.Stack("ssm", (tfm.collapse(),))).step
        is emcore.em_step_collapsed
    )
    assert (
        tfm.resolve(
            tfm.Stack("ar", (tfm.collapse(), tfm.steady_tail(16)))
        ).step
        is emcore._ar_steady_step_for(16, 0)
    )


@pytest.mark.multidevice
def test_resolve_sharded_steps_are_the_mesh_cached_objects():
    assert (
        tfm.resolve(tfm.Stack("ssm", (tfm.shard(2),))).step
        is ssm._sharded_step_for(2)
    )
    assert (
        tfm.resolve(tfm.Stack("ar", (tfm.collapse(), tfm.shard(2)))).step
        is emcore._ar_sharded_step_for(2)
    )
    res = tfm.resolve(
        tfm.Stack("ar", (tfm.collapse(), tfm.steady_tail(16), tfm.shard(2)))
    )
    assert res.step is emcore._ar_steady_sharded_step_for(16, 0, 2)
    assert res.carry == "ar_steady" and res.n_shards == 2


# ---------------------------------------------------------------------------
# 2. invalid stacks fail at resolve time
# ---------------------------------------------------------------------------


def test_resolve_rejects_invalid_stacks():
    with pytest.raises(ValueError, match="unknown core"):
        tfm.resolve(tfm.Stack("svar"))
    with pytest.raises(ValueError, match="unknown transform kind"):
        tfm.resolve(tfm.Stack("ssm", (tfm.Transform("fuse"),)))
    with pytest.raises(ValueError, match="duplicate"):
        tfm.resolve(tfm.Stack("ssm", (tfm.collapse(), tfm.collapse())))
    with pytest.raises(ValueError, match="steady x shard"):
        tfm.resolve(
            tfm.Stack("ssm", (tfm.steady_tail(16), tfm.shard(2)))
        )
    with pytest.raises(ValueError, match="require 'collapse'"):
        tfm.resolve(tfm.Stack("ar", (tfm.steady_tail(16),)))
    with pytest.raises(ValueError, match="no step transforms"):
        tfm.resolve(tfm.Stack("ssm.sqrt", (tfm.collapse(),)))
    with pytest.raises(ValueError, match="no step transforms"):
        tfm.resolve(tfm.Stack("mf", (tfm.collapse(),)))


def test_wrap_unwrap_params_roundtrip(rng):
    N, r, p = 6, 2, 1
    params = ssm_ar.SSMARParams(
        lam=jnp.asarray(rng.standard_normal((N, r))),
        phi=jnp.zeros(N),
        sigv2=jnp.ones(N),
        A=0.5 * jnp.eye(r)[None],
        Q=jnp.eye(r),
    )
    res = tfm.resolve(
        tfm.Stack("ar", (tfm.collapse(), tfm.steady_tail(16)))
    )
    state = tfm.wrap_params(res, params)
    k = r * max(p, 2)
    assert isinstance(state, emcore.ARSteadyState)
    assert state.Pp.shape == (k, k)
    assert tfm.unwrap_params(res, state) is params
    res_bare = tfm.resolve(tfm.Stack("ar", (tfm.collapse(),)))
    assert tfm.wrap_params(res_bare, params) is params


# ---------------------------------------------------------------------------
# 3. composed-step exactness
# ---------------------------------------------------------------------------


def test_em_step_collapsed_matches_em_step_stats(rng):
    T_, N = 48, 14
    f = rng.standard_normal((T_, 2))
    lam = rng.standard_normal((N, 2))
    x = f @ lam.T + 0.5 * rng.standard_normal((T_, N))
    mask = np.ones((T_, N), bool)
    mask[:5, 0] = False
    mask[40:, 3] = False
    xz = jnp.asarray(np.where(mask, x, 0.0))
    m = jnp.asarray(mask)
    stats = ssm.compute_panel_stats(xz, m)
    params = ssm.SSMParams(
        lam=jnp.asarray(lam + 0.1 * rng.standard_normal((N, 2))),
        R=jnp.ones(N),
        A=0.5 * jnp.eye(2)[None],
        Q=jnp.eye(2),
    )
    pa = pb = params
    for _ in range(4):
        pa, lla = ssm.em_step_stats(pa, xz, m, stats)
        pb, llb = emcore.em_step_collapsed(pb, xz, m, stats)
        assert abs(float(lla) - float(llb)) <= 1e-10 * (1 + abs(float(lla)))
        for a, b in zip(pa, pb):
            np.testing.assert_allclose(a, b, atol=1e-10)


def _ar_panel(rng, T_=220, N=20, r=2):
    """Contiguous-prefix missingness only (the QD-exact mask class)."""
    phi_true = rng.uniform(-0.5, 0.7, N)
    lam = rng.standard_normal((N, r))
    f = np.zeros((T_, r))
    for t in range(1, T_):
        f[t] = 0.6 * f[t - 1] + 0.5 * rng.standard_normal(r)
    e = np.zeros((T_, N))
    for t in range(1, T_):
        e[t] = phi_true * e[t - 1] + 0.4 * rng.standard_normal(N)
    x = f @ lam.T + e
    for i in range(6):
        x[: int(rng.integers(1, 6)), i] = np.nan
    return x


def _fit_ar(x, **kw):
    cfg = DFMConfig(nfac_u=2, n_factorlag=1)
    return ssm_ar.estimate_dfm_em_ar(
        x, np.ones(x.shape[1]), 0, x.shape[0] - 1, cfg,
        max_em_iter=10, method="collapsed", **kw,
    )


def test_ar_steady_stack_matches_plain_collapsed(rng):
    x = _ar_panel(rng)
    base = _fit_ar(x)
    st = _fit_ar(x, steady=True)
    for a, b in zip(base.params, st.params):
        np.testing.assert_allclose(a, b, atol=1e-10)
    np.testing.assert_allclose(
        np.asarray(base.loglik_path), np.asarray(st.loglik_path),
        atol=1e-8 * (1 + abs(float(base.loglik_path[-1]))),
    )
    np.testing.assert_allclose(base.factors, st.factors, atol=1e-10)


@pytest.mark.multidevice
def test_ar_sharded_and_all_stacks_match_plain_collapsed(rng):
    x = _ar_panel(rng)
    base = _fit_ar(x)
    sh = _fit_ar(x, n_shards=8)  # N=20 pads to 24: 3 series per shard
    assert sh.params.lam.shape == base.params.lam.shape
    for a, b in zip(base.params, sh.params):
        np.testing.assert_allclose(a, b, atol=1e-10)
    both = _fit_ar(x, steady=True, n_shards=8)
    for a, b in zip(base.params, both.params):
        np.testing.assert_allclose(a, b, atol=1e-10)
    np.testing.assert_allclose(base.factors, both.factors, atol=1e-10)


def test_ar_series_padding_is_inert(rng):
    x = _ar_panel(rng, T_=60, N=10)
    mask = ~np.isnan(x)
    xz = jnp.asarray(np.where(mask, x, 0.0))
    m = jnp.asarray(mask)
    N = x.shape[1]
    params = ssm_ar.SSMARParams(
        lam=jnp.asarray(rng.standard_normal((N, 2))),
        phi=jnp.zeros(N),
        sigv2=jnp.full((N,), 0.5),
        A=0.5 * jnp.eye(2)[None],
        Q=jnp.eye(2),
    )
    Npad = N + 6
    xz_p = jnp.concatenate([xz, jnp.zeros((x.shape[0], 6))], axis=1)
    m_p = jnp.concatenate([m, jnp.zeros((x.shape[0], 6), bool)], axis=1)
    params_p = emcore.pad_ar_params(params, Npad)
    assert params_p.lam.shape[0] == Npad
    qd = ssm_ar.compute_qd_stats(xz, m)
    qd_p = ssm_ar.compute_qd_stats(xz_p, m_p)
    p1, ll1 = ssm_ar.em_step_ar_qd(params, xz, qd)
    p2, ll2 = ssm_ar.em_step_ar_qd(params_p, xz_p, qd_p)
    assert abs(float(ll1) - float(ll2)) <= 1e-12 * (1 + abs(float(ll1)))
    p2u = emcore.unpad_ar_params(p2, N)
    for a, b in zip(p1, p2u):
        np.testing.assert_allclose(a, b, atol=1e-12)


# ---------------------------------------------------------------------------
# 4. derived AOT plan
# ---------------------------------------------------------------------------

# the complete EM-family kernel key set the pre-stack hand enumeration
# produced for a maximal spec (t_star set, n_shards > 1, em_batch > 0,
# sharded kernels requested) — the derived plan must reproduce it exactly
FROZEN_EM_KEYS = {
    "em_step_stats",
    "em_step",
    "em_step_sqrt",
    "em_step_sqrt_collapsed",
    "em_step_ar",
    "em_step_ar_qd",
    "em_loop",
    "em_loop_guarded",
    "em_step_steady",
    "em_loop@steady",
    "em_loop_guarded@steady",
    "em_step_sharded",
    "em_loop_guarded@sharded",
    "em_loop_batched",
}


def _maximal_spec(**kw):
    base = dict(
        T=60, N=12, r=2, p=1, dtype=str(np.dtype(float)),
        max_em_iter=4, t_star=16, n_shards=2, em_batch=2,
        kernels=cc.CompileSpec.kernels
        + ("em_step_sharded", "em_loop_guarded@sharded"),
    )
    base.update(kw)
    return cc.CompileSpec(**base)


def test_enumerate_stacks_reproduces_frozen_key_set():
    entries = tfm.enumerate_stacks(_maximal_spec())
    keys = [e.key for e in entries]
    assert len(keys) == len(set(keys)), "duplicate derived plan keys"
    assert set(keys) == FROZEN_EM_KEYS
    # composed kernels are opt-in by name: absent unless requested
    spec2 = _maximal_spec(
        kernels=cc.CompileSpec.kernels
        + ("em_step_sharded", "em_loop_guarded@sharded",
           "em_step_collapsed", "em_step_ar_steady",
           "em_step_ar_sharded", "em_step_ar_all"),
    )
    keys2 = {e.key for e in tfm.enumerate_stacks(spec2)}
    assert keys2 == FROZEN_EM_KEYS | {
        "em_step_collapsed", "em_step_ar_steady",
        "em_step_ar_sharded", "em_step_ar_all",
    }
    # gating: the composed AR kernels need their static inputs
    spec3 = _maximal_spec(
        t_star=None, n_shards=0, em_batch=0,
        kernels=("em_step_ar_steady", "em_step_ar_sharded",
                 "em_step_ar_all"),
    )
    assert tfm.enumerate_stacks(spec3) == []


@pytest.mark.multidevice
def test_kernel_plan_keys_match_frozen_set():
    """Every stack reachable from the spec registers exactly one plan
    entry, and the derived registry equals the old hand-enumerated set
    plus the two non-EM cores — no orphans, no duplicates."""
    plans = cc._kernel_plan(_maximal_spec())
    assert set(plans) == FROZEN_EM_KEYS | {"als_core", "bootstrap_core"}


def test_composed_kernels_precompile_once_then_hit_warm():
    cc.reset_counters()
    spec = cc.CompileSpec(
        T=60, N=12, r=2, p=1, dtype=str(np.dtype(float)),
        max_em_iter=4, t_star=16,
        kernels=("em_step_collapsed", "em_step_ar_qd",
                 "em_step_ar_steady"),
    )
    r1 = cc.precompile(spec)
    for k in spec.kernels:
        assert not r1["kernels"][k]["aot_cached"]
        assert cc.counters()[k]["compiles"] == 1
    r2 = cc.precompile(spec)
    for k in spec.kernels:
        assert r2["kernels"][k]["aot_cached"]
        c = cc.counters()[k]
        assert c["compiles"] == 1 and c["aot_hits"] == 1


# ---------------------------------------------------------------------------
# 5. dense-fallback UX (satellite: the warning names the offenders)
# ---------------------------------------------------------------------------


def test_gap_report_and_fallback_warning_name_series(rng, tmp_path,
                                                     monkeypatch):
    x = _ar_panel(rng, T_=60, N=10)
    x[25, 2] = np.nan  # interior gaps: outside the QD mask class
    x[30:33, 5] = np.nan
    mask = ~np.isnan(x)
    bad, gaps = ssm_ar.qd_gap_report(mask)
    assert list(bad) == [2, 5]
    assert list(gaps) == [25, 30]

    sink = str(tmp_path / "runs.jsonl")
    monkeypatch.setenv("DFM_TELEMETRY", sink)
    monkeypatch.setattr(T, "_explicit_enabled", None)
    monkeypatch.setattr(T, "_explicit_sink", None)
    T.reset()
    with pytest.warns(UserWarning, match=r"2 \(first gap at t=25\)"):
        res = _fit_ar(x)
    assert np.isfinite(res.loglik_path[-1])
    rec = [
        r for r in T.records() if r["entry"] == "estimate_dfm_em_ar"
    ][-1]
    assert rec["collapse_gated"] is True
    assert rec["gap_series"] == [2, 5]
