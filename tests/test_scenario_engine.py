"""Scenario engine (scenarios/): vmapped multi-chain Gibbs with guarded
divergence dropping, conditional/stress/draw fan-out, batched news, and
the serving + AOT wiring.

The two load-bearing pins:

* chain parity — every lane of the scan-outside/vmap-inside multi-chain
  program reproduces a sequential `models.bayes._chain` run of the same
  key (1e-10);
* the divergence drill — a ``nan_draw@k`` injection freezes exactly the
  hit chain, and the surviving chains' draws are BIT-identical to a
  fault-free run (vmap lanes are elementwise; dropping happens host-side
  after normalization, never by reshaping the device batch).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.bayes import (
    BayesPriors,
    _chain,
    ess,
    estimate_dfm_bayes,
    rhat,
)
from dynamic_factor_models_tpu.models.dfm import DFMConfig
from dynamic_factor_models_tpu.models.forecast import conditional_forecast
from dynamic_factor_models_tpu.models.news import (
    nowcast_news,
    nowcast_news_batch,
)
from dynamic_factor_models_tpu.models.ssm import SSMParams
from dynamic_factor_models_tpu.scenarios import (
    ScenarioRequest,
    conditional_fan,
    draw_fan,
    run_scenario,
    sample_chains,
    stress_fan,
)
from dynamic_factor_models_tpu.utils import faults

pytestmark = pytest.mark.scenario_engine


def _params(N=8, r=2, p=2, seed=0):
    rng = np.random.default_rng(seed)
    return SSMParams(
        lam=jnp.asarray(rng.standard_normal((N, r))),
        R=jnp.ones(N),
        A=jnp.zeros((p, r, r)).at[0].set(0.5 * jnp.eye(r)),
        Q=jnp.eye(r),
    )


def _panel(params, T=60, miss=0.1, seed=0):
    rng = np.random.default_rng(seed)
    N, r = params.lam.shape
    f = rng.standard_normal((T, r)).cumsum(0) * 0.3
    x = f @ np.asarray(params.lam).T + rng.standard_normal((T, N))
    x[rng.random((T, N)) < miss] = np.nan
    return x


def _prior_tuple():
    pr = BayesPriors()
    return (
        float(pr.lam_scale), float(pr.r_shape), float(pr.r_rate),
        float(pr.q_df_extra), float(pr.q_scale),
    )


@pytest.fixture(scope="module")
def gibbs_setup():
    params = _params()
    x = _panel(params, miss=0.0)
    xz = jnp.asarray((x - x.mean(0)) / x.std(0))
    m = jnp.ones(xz.shape)
    kw = dict(n_burn=10, n_keep=8, thin=2, p=2, priors=_prior_tuple())
    keys = jax.random.split(jax.random.PRNGKey(0), 4)
    return params, xz, m, keys, kw


class TestMultiChainGibbs:
    def test_parity_with_sequential_chains(self, gibbs_setup):
        """Each vmapped lane == a sequential single-chain run (1e-10)."""
        params, xz, m, keys, kw = gibbs_setup
        mc = sample_chains(keys, params, xz, m, **kw)
        assert (mc.health == 0).all()
        stack = (mc.factor_draws, mc.lam_draws, mc.r_draws,
                 mc.a_draws, mc.q_draws)
        for c in range(4):
            ref = _chain(keys[c], params, xz, m, **kw)
            for a, b in zip(ref[:5], stack):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b[c]), atol=1e-10
                )
            np.testing.assert_allclose(
                np.asarray(ref[5]), np.asarray(mc.loglik_path[c]),
                atol=1e-10,
            )

    def test_divergent_chain_frozen_survivors_bit_identical(
        self, gibbs_setup
    ):
        """nan_draw@3 hits chain 0 at global sweep 3: that chain is
        flagged and frozen (finite stale draws, constant loglik tail),
        and chains 1..3 match the clean run bit for bit."""
        params, xz, m, keys, kw = gibbs_setup
        clean = sample_chains(keys, params, xz, m, **kw)
        with faults.inject("nan_draw@3"):
            inj = sample_chains(keys, params, xz, m, **kw)
        assert inj.health[0] == 1 and (inj.health[1:] == 0).all()
        for a, b in zip(clean[:5], inj[:5]):
            np.testing.assert_array_equal(
                np.asarray(a[1:]), np.asarray(b[1:])
            )
        ll0 = np.asarray(inj.loglik_path[0])
        assert np.isnan(ll0[2])  # the injected sweep (1-based 3)
        # frozen: post-hit sweeps rerun from the rolled-back state
        assert np.ptp(ll0[3:]) == 0.0
        # stale-but-finite kept draws (keep phase starts after the hit)
        assert np.isfinite(np.asarray(inj.factor_draws[0])).all()

    def test_estimate_drops_divergent_chain(self):
        """Public API: the hit chain is excluded from the posterior,
        health and the full loglik trace are reported."""
        x = _panel(_params(N=12, r=1), T=120, miss=0.0)
        args = (
            jnp.asarray(x), np.ones(12, np.int64), 0, 119,
            DFMConfig(nfac_u=1, n_factorlag=1),
        )
        kw = dict(n_keep=10, n_burn=10, n_chains=3, seed=0)
        clean = estimate_dfm_bayes(*args, **kw)
        with faults.inject("nan_draw@5"):
            res = estimate_dfm_bayes(*args, **kw)
        assert list(res.chain_health) == [1, 0, 0]
        assert res.factor_draws.shape[0] == 2
        assert res.loglik_path.shape == (3, 20)
        np.testing.assert_array_equal(
            np.asarray(res.factor_draws),
            np.asarray(clean.factor_draws[1:]),
        )
        assert np.isfinite(res.rhat_loglik)
        with faults.inject("nan_draw@5"):
            with pytest.raises(RuntimeError, match="every Gibbs chain"):
                estimate_dfm_bayes(*args, n_keep=10, n_burn=10,
                                   n_chains=1, seed=0)

    def test_nan_draw_grammar(self):
        plan = faults.parse_spec("nan_draw@7")
        assert plan.nan_draw == 7 and plan.any()
        with pytest.raises(ValueError):
            faults.parse_spec("nan_draw")  # explicit site required


class TestDiagnostics:
    def test_rhat_shape_dispatch(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((4, 500))
        assert isinstance(rhat(x), float) and rhat(x) < 1.05
        # 1-D draws: one chain split in halves — still a float
        assert isinstance(rhat(x[0]), float)
        r3 = rhat(rng.standard_normal((4, 500, 3)))
        assert np.asarray(r3).shape == (3,)
        # a mean-shifted chain must blow split-Rhat up
        y = x.copy()
        y[0] += 10.0
        assert rhat(y) > 1.5

    def test_ess_sane(self):
        rng = np.random.default_rng(1)
        x = rng.standard_normal((4, 500))
        e = ess(x)
        assert 0 < e <= 2000.0
        # heavy autocorrelation collapses the effective sample size
        ar = np.zeros((2, 500))
        eps = rng.standard_normal((2, 500))
        for t in range(1, 500):
            ar[:, t] = 0.98 * ar[:, t - 1] + eps[:, t]
        assert ess(ar) < ess(x) / 4
        assert np.asarray(ess(rng.standard_normal((2, 200, 3)))).shape \
            == (3,)


class TestFanout:
    def test_conditional_fan_matches_looped_forecast(self):
        """Every fan lane == conditional_forecast of that lane (1e-12)."""
        params = _params()
        x = _panel(params)
        h, N = 6, params.lam.shape[0]
        rng = np.random.default_rng(2)
        cond = np.full((3, h, N), np.nan)
        cond[1, 0, :2] = 1.5  # pin two series one step out
        cond[2, :, 0] = rng.standard_normal(h)
        mean, sd, f, Pf = conditional_fan(params, x, h, cond)
        assert mean.shape == (3, h, N)
        for s in range(3):
            ref = conditional_forecast(params, x, h, cond[s])
            np.testing.assert_allclose(
                np.asarray(mean[s]), np.asarray(ref.mean), atol=1e-12
            )
            np.testing.assert_allclose(
                np.asarray(sd[s]), np.asarray(ref.sd), atol=1e-12
            )
            np.testing.assert_allclose(
                np.asarray(f[s]), np.asarray(ref.factor_mean),
                atol=1e-12,
            )

    def test_draw_fan_shapes_and_reproducibility(self):
        params = _params()
        x = _panel(params)
        f1, y1, ll1 = draw_fan(params, x, 4, 16, seed=7)
        f2, y2, _ = draw_fan(params, x, 4, 16, seed=7)
        assert y1.shape == (1, 16, 4, 8) and f1.shape == (1, 16, 4, 2)
        np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
        assert np.isfinite(np.asarray(ll1)).all()
        # draw spread brackets the smoothed mean
        mean, *_ = conditional_fan(params, x, 4)
        dm = np.asarray(y1).mean(axis=1)
        assert np.abs(dm - np.asarray(mean)).max() < 2.0

    def test_stress_fan_superposition(self):
        """Zero shock == baseline; response is linear in the shock."""
        params = _params()
        x = _panel(params)
        shocks = np.vstack([np.zeros(2), np.eye(2), 2 * np.eye(2)[:1]])
        mean, sd, f = stress_fan(params, x, 5, shocks)
        base, base_sd, base_f = (
            np.asarray(mean[0]), np.asarray(sd[0]), np.asarray(f[0])
        )
        b0, *_ = conditional_fan(params, x, 5)
        np.testing.assert_allclose(base, np.asarray(b0[0]), atol=1e-12)
        np.testing.assert_allclose(
            np.asarray(mean[3]) - base, 2 * (np.asarray(mean[1]) - base),
            atol=1e-10,
        )
        np.testing.assert_array_equal(np.asarray(sd[1]), base_sd)

    def test_news_batch_matches_scalar(self):
        params = _params(N=6)
        T, N = 40, 6
        x_new = _panel(params, T=T, miss=0.0, seed=3)
        x_new[-1, :2] = np.nan
        x_old = x_new.copy()
        x_old[-1, 2] = np.nan
        x_old[-2, 3] = np.nan
        targets = [(T - 1, 0), (T - 1, 1)]
        nb = nowcast_news_batch(params, x_old, x_new, targets)
        assert nb.news.shape == (2, 2)
        for j, tgt in enumerate(targets):
            sc = nowcast_news(params, x_old, x_new, tgt)
            np.testing.assert_allclose(
                np.asarray(sc.news), np.asarray(nb.news[:, j]),
                atol=1e-12,
            )
            assert abs(sc.total_revision - nb.total_revision[j]) < 1e-12
        # telescoping exactness per target
        np.testing.assert_allclose(
            np.asarray(nb.news).sum(0), nb.total_revision, atol=1e-10
        )


class TestScenarioAPI:
    def test_run_scenario_dispatch(self):
        params = _params()
        x = _panel(params)
        res = run_scenario(params, x, ScenarioRequest(
            kind="conditional_fan", horizon=4, n_draws=5,
        ))
        assert res.mean.shape == (1, 4, 8)
        assert res.draws.shape == (1, 5, 4, 8)
        res = run_scenario(params, x, ScenarioRequest(
            kind="stress", horizon=3, shocks=np.eye(2),
        ))
        assert res.mean.shape == (2, 3, 8) and res.draws is None
        with pytest.raises(ValueError, match="unknown scenario kind"):
            run_scenario(params, x, ScenarioRequest(kind="frobnicate"))
        with pytest.raises(ValueError, match="shocks"):
            run_scenario(params, x, ScenarioRequest(kind="stress"))
        with pytest.raises(ValueError, match="n_draws"):
            run_scenario(params, x, ScenarioRequest(kind="draw_fan"))

    def test_engine_scenario_route(self):
        from dynamic_factor_models_tpu.serving.engine import ServingEngine

        rng = np.random.default_rng(5)
        T, N = 48, 8
        x = (rng.standard_normal((T, 4)).cumsum(0) * 0.1
             @ rng.standard_normal((N, 4)).T
             + 0.5 * rng.standard_normal((T, N)))
        eng = ServingEngine()
        eng.register("acme", x)
        res = eng.handle({
            "kind": "scenario", "tenant": "acme",
            "scenario": {"kind": "stress", "horizon": 6,
                         "shocks": np.eye(4)[:2].tolist()},
        })
        assert res.ok and np.asarray(res.result.mean).shape == (2, 6, N)
        res = eng.handle({
            "kind": "scenario", "tenant": "acme",
            "scenario": {"kind": "draw_fan", "horizon": 4, "n_draws": 6},
        })
        assert res.ok and np.asarray(res.result.draws).shape == (1, 6, 4, N)
        # spec errors come back as typed client-error envelopes, never
        # raw ValueError/TypeError out of the request loop
        res = eng.handle({"kind": "scenario", "tenant": "acme",
                          "scenario": {"kind": "nope"}})
        assert not res.ok and res.error.category == "client_error"
        assert res.error.code == "bad_scenario"
        assert "unknown scenario kind" in res.error.message
        res = eng.handle({"kind": "scenario", "tenant": "acme",
                          "scenario": {"kind": "stress", "bogus": 1}})
        assert not res.ok and res.error.category == "client_error"
        assert res.error.code == "unknown_scenario_field"
        assert res.error.field == "scenario.bogus"

    def test_aot_registration_serves_fans(self):
        """precompile(CompileSpec(scenario_draws=...)) registers the
        three fan kernels; matching production calls dispatch to the
        executables (aot_hits) instead of re-tracing."""
        from dynamic_factor_models_tpu.scenarios.fanout import (
            forecast_fan,
        )
        from dynamic_factor_models_tpu.utils.compile import (
            CompileSpec,
            counters,
            precompile,
        )

        params = _params(N=6)
        x = _panel(params, T=32, miss=0.0, seed=9)
        rep = precompile(CompileSpec(
            T=32, N=6, r=2, p=2, dtype="float64", kernels=(),
            bucket=False, scenario_draws=8, scenario_paths=2,
            scenario_horizon=5,
        ))
        assert {"scenario_fan", "scenario_cond_fan",
                "scenario_draw_fan"} <= set(rep["kernels"])

        def hits(name):
            return counters()[name]["aot_hits"]

        h0 = hits("scenario_cond_fan")
        conditional_fan(params, x, 5, np.full((2, 5, 6), np.nan))
        assert hits("scenario_cond_fan") == h0 + 1
        h0 = hits("scenario_draw_fan")
        draw_fan(params, x, 5, 8, np.full((2, 5, 6), np.nan))
        assert hits("scenario_draw_fan") == h0 + 1
        h0 = hits("scenario_fan")
        D = 8
        forecast_fan(
            jnp.broadcast_to(params.lam, (D, 6, 2)),
            jnp.broadcast_to(params.R, (D, 6)),
            jnp.broadcast_to(params.A, (D, 2, 2, 2)),
            jnp.broadcast_to(params.Q, (D, 2, 2)),
            jnp.zeros((D, 4)),
            jax.random.split(jax.random.PRNGKey(0), D),
            5,
        )
        assert hits("scenario_fan") == h0 + 1
