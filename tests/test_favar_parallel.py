"""FAVAR wild bootstrap + mesh sharding tests (virtual 8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.dfm import DFMConfig, estimate_factor
from dynamic_factor_models_tpu.models.favar import wild_bootstrap_irfs
from dynamic_factor_models_tpu.parallel.mesh import make_mesh, shard_over


@pytest.fixture(scope="module")
def factors(dataset_real):
    F, _ = estimate_factor(
        dataset_real.bpdata, dataset_real.inclcode, 2, 223, DFMConfig(nfac_u=4)
    )
    return F


def test_bootstrap_bands_cover_point(factors):
    bs = wild_bootstrap_irfs(factors, 4, 2, 223, horizon=16, n_reps=200, seed=3)
    assert bs.draws.shape == (200, 4, 16, 4)
    pt = np.asarray(bs.point)
    lo, hi = np.asarray(bs.quantiles[0]), np.asarray(bs.quantiles[-1])
    assert ((pt >= lo) & (pt <= hi)).mean() > 0.9
    # median tracks the point estimate
    med = np.asarray(bs.quantiles[2])
    assert np.corrcoef(med.ravel(), pt.ravel())[0, 1] > 0.99


def test_bootstrap_sharded_equals_unsharded(factors):
    mesh = make_mesh(8, ("rep",))
    bs_sh = wild_bootstrap_irfs(factors, 4, 2, 223, horizon=8, n_reps=64, mesh=mesh)
    bs_1 = wild_bootstrap_irfs(factors, 4, 2, 223, horizon=8, n_reps=64, mesh=None)
    np.testing.assert_allclose(
        np.asarray(bs_sh.draws), np.asarray(bs_1.draws), atol=1e-10
    )
    assert "rep" in str(bs_sh.draws.sharding)


def test_bootstrap_rejects_ragged_window(dataset_real):
    y = np.asarray(dataset_real.bpdata[:, :3]).copy()
    y[50, 0] = np.nan  # interior hole
    with pytest.raises(ValueError, match="contiguous"):
        wild_bootstrap_irfs(jnp.asarray(y), 2, 0, 223, n_reps=8)


def test_mesh_helpers():
    mesh = make_mesh(8, ("rep",))
    x = jnp.arange(16.0).reshape(16, 1)
    xs = shard_over(mesh, "rep", x)
    assert xs.sharding.mesh.shape["rep"] == 8
    np.testing.assert_allclose(np.asarray(xs), np.asarray(x))


@pytest.mark.slow
def test_graft_entry_and_dryrun():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    params, ll = out
    assert np.isfinite(float(ll))
    g.dryrun_multichip(8)


def test_bootstrap_resumable_matches_uninterrupted(factors, tmp_path):
    from dynamic_factor_models_tpu.models.favar import wild_bootstrap_irfs_resumable

    ckpt = str(tmp_path / "boot.npz")
    kw = dict(nlag=2, initperiod=0, lastperiod=factors.shape[0] - 1,
              horizon=6, n_reps=24, chunk_reps=10, seed=3)
    full = wild_bootstrap_irfs_resumable(factors, checkpoint_path=ckpt, **kw)
    assert np.isfinite(np.asarray(full.draws)).all()

    # simulate preemption: rewind the checkpoint to after chunk 1 and resume
    with np.load(ckpt) as z:
        np.savez(ckpt, draws=z["draws"][:1], next_chunk=1,
                 spec=z["spec"], fingerprint=z["fingerprint"])
    resumed = wild_bootstrap_irfs_resumable(factors, checkpoint_path=ckpt, **kw)
    np.testing.assert_array_equal(np.asarray(resumed.draws), np.asarray(full.draws))


def test_bootstrap_resumable_discards_stale_checkpoint(factors, tmp_path):
    from dynamic_factor_models_tpu.models.favar import wild_bootstrap_irfs_resumable

    ckpt = str(tmp_path / "boot.npz")
    kw = dict(initperiod=0, lastperiod=factors.shape[0] - 1,
              horizon=6, n_reps=10, chunk_reps=10, seed=3)
    wild_bootstrap_irfs_resumable(factors, nlag=2, checkpoint_path=ckpt, **kw)
    # same shapes, different model spec: checkpoint must be discarded
    again = wild_bootstrap_irfs_resumable(factors, nlag=4, checkpoint_path=ckpt, **kw)
    fresh = wild_bootstrap_irfs_resumable(
        factors, nlag=4, checkpoint_path=str(tmp_path / "b2.npz"), **kw
    )
    np.testing.assert_array_equal(np.asarray(again.draws), np.asarray(fresh.draws))


def test_distributed_helpers_single_process():
    from dynamic_factor_models_tpu.parallel.distributed import (
        global_mesh,
        initialize_distributed,
    )

    # no coordinator configured: must be a no-op returning False
    assert initialize_distributed() is False
    mesh = global_mesh()
    assert mesh.devices.size == len(jax.devices())
    mesh2 = global_mesh(axis_names=("dp", "sp"), shape=(4, 2))
    assert mesh2.shape == {"dp": 4, "sp": 2}
    with pytest.raises(ValueError, match="tile"):
        global_mesh(axis_names=("dp",), shape=(3,))


def test_probe_default_device_cpu_short_circuit():
    """Under the suite's cpu-only platform config the liveness probe must
    short-circuit without spawning a subprocess-visible delay."""
    import time

    from dynamic_factor_models_tpu.utils.backend import probe_default_device

    t0 = time.perf_counter()
    ok, detail = probe_default_device(5)
    assert ok and "cpu-only" in detail
    assert time.perf_counter() - t0 < 1.0


class TestSeriesIRFs:
    """Bootstrap bands pushed through the loadings to series space."""

    @pytest.fixture(scope="class")
    def boot(self):
        rng = np.random.default_rng(2)
        y = np.zeros((250, 3))
        A1 = np.array([[0.5, 0.1, 0.0], [0.0, 0.4, 0.1], [0.1, 0.0, 0.3]])
        for t in range(1, 250):
            y[t] = A1 @ y[t - 1] + rng.standard_normal(3)
        return wild_bootstrap_irfs(
            jnp.asarray(y), 1, 0, 249, horizon=8, n_reps=64, seed=0
        )

    def test_contraction_matches_point(self, boot):
        from dynamic_factor_models_tpu.models.favar import series_irfs

        lam = np.random.default_rng(3).standard_normal((10, 3))
        s = series_irfs(boot, lam)
        assert s.point.shape == (10, 8, 3)
        assert s.quantiles.shape == (5, 10, 8, 3)
        np.testing.assert_allclose(
            np.asarray(s.point),
            np.einsum("nk,khj->nhj", lam, np.asarray(boot.point)),
            rtol=1e-12,
        )
        # series-space bands bracket the series-space point estimate
        lo, hi = np.asarray(s.quantiles[0]), np.asarray(s.quantiles[-1])
        inside = (np.asarray(s.point) >= lo) & (np.asarray(s.point) <= hi)
        assert inside.mean() > 0.9
        assert (np.diff(np.asarray(s.quantiles), axis=0) >= -1e-12).all()

    def test_subset_and_scale(self, boot):
        from dynamic_factor_models_tpu.models.favar import series_irfs

        lam = np.random.default_rng(4).standard_normal((6, 3))
        full = series_irfs(boot, lam)
        sub = series_irfs(boot, lam, series_idx=[1, 4])
        np.testing.assert_allclose(
            np.asarray(sub.point), np.asarray(full.point)[[1, 4]], rtol=1e-12
        )
        np.testing.assert_allclose(
            np.asarray(sub.quantiles),
            np.asarray(full.quantiles)[:, [1, 4]],
            rtol=1e-12,
        )
        scale = np.array([2.0] * 6)
        scaled = series_irfs(boot, lam, scale=scale)
        np.testing.assert_allclose(
            np.asarray(scaled.quantiles), 2.0 * np.asarray(full.quantiles),
            rtol=1e-12,
        )

    def test_dimension_mismatch_raises(self, boot):
        from dynamic_factor_models_tpu.models.favar import series_irfs

        with pytest.raises(ValueError, match="factor columns"):
            series_irfs(boot, np.zeros((5, 4)))

    def test_out_of_range_series_idx_raises(self, boot):
        from dynamic_factor_models_tpu.models.favar import series_irfs

        lam = np.zeros((5, 3))
        with pytest.raises(IndexError, match="out of range"):
            series_irfs(boot, lam, series_idx=[999])


class TestBlockBootstrap:
    def test_block_bootstrap_brackets_point(self):
        from dynamic_factor_models_tpu.models.favar import block_bootstrap_irfs

        rng = np.random.default_rng(0)
        y = np.zeros((300, 3))
        A1 = np.array([[0.5, 0.1, 0.0], [0.0, 0.4, 0.1], [0.1, 0.0, 0.3]])
        for t in range(1, 300):
            y[t] = A1 @ y[t - 1] + rng.standard_normal(3)
        bs = block_bootstrap_irfs(
            jnp.asarray(y), 1, 0, 299, horizon=8, n_reps=64, block=8, seed=0
        )
        assert bs.draws.shape == (64, 3, 8, 3)
        assert np.isfinite(np.asarray(bs.draws)).all()
        lo, hi = np.asarray(bs.quantiles[0]), np.asarray(bs.quantiles[-1])
        frac = np.mean((np.asarray(bs.point) >= lo) & (np.asarray(bs.point) <= hi))
        assert frac > 0.9

    def test_block_validation(self):
        from dynamic_factor_models_tpu.models.favar import block_bootstrap_irfs

        y = np.random.default_rng(1).standard_normal((50, 2))
        with pytest.raises(ValueError, match="block"):
            block_bootstrap_irfs(jnp.asarray(y), 1, 0, 49, n_reps=4, block=0)


class TestForecastFan:
    """Bootstrap forecast fans (parameter + shock uncertainty)."""

    @staticmethod
    def _ar_panel(T=360, seed=5):
        rng = np.random.default_rng(seed)
        A1 = np.array([[0.6, 0.1], [0.0, 0.5]])
        y = np.zeros((T, 2))
        for t in range(1, T):
            y[t] = A1 @ y[t - 1] + rng.standard_normal(2)
        return y

    def test_point_matches_forecast_factors_and_median_tracks(self):
        from dynamic_factor_models_tpu.models.favar import bootstrap_forecast_fan
        from dynamic_factor_models_tpu.models.forecast import forecast_factors
        from dynamic_factor_models_tpu.models.var import estimate_var

        y = self._ar_panel()
        fan = bootstrap_forecast_fan(jnp.asarray(y), 1, 0, 299, horizon=8,
                                     n_reps=200, seed=0)
        var = estimate_var(jnp.asarray(y[:300]), 1)
        path = forecast_factors(var, jnp.asarray(y[:300]), 8)
        np.testing.assert_allclose(np.asarray(fan.point), np.asarray(path),
                                   atol=1e-8)
        med = np.asarray(fan.quantiles[2])
        assert np.abs(med - np.asarray(fan.point)).max() < 0.5
        assert (np.diff(np.asarray(fan.quantiles), axis=0) >= -1e-12).all()

    def test_band_covers_realized_future(self):
        from dynamic_factor_models_tpu.models.favar import bootstrap_forecast_fan

        hits, total = 0, 0
        for seed in range(4):
            y = self._ar_panel(seed=seed)
            fan = bootstrap_forecast_fan(jnp.asarray(y), 1, 0, 299, horizon=8,
                                         n_reps=300, seed=seed)
            lo, hi = np.asarray(fan.quantiles[0]), np.asarray(fan.quantiles[-1])
            realized = y[300:308]
            hits += ((realized >= lo) & (realized <= hi)).sum()
            total += realized.size
        cover = hits / total
        assert 0.75 < cover <= 1.0, f"5-95% fan coverage {cover}"

    def test_series_fan_contraction(self):
        from dynamic_factor_models_tpu.models.favar import (
            bootstrap_forecast_fan,
            series_forecast_fan,
        )

        y = self._ar_panel()
        fan = bootstrap_forecast_fan(jnp.asarray(y), 1, 0, 299, horizon=6,
                                     n_reps=100, seed=1)
        lam = np.random.default_rng(0).standard_normal((7, 2))
        const = np.arange(7.0)
        s = series_forecast_fan(fan, lam, const=const)
        assert s.point.shape == (7, 6)
        assert s.quantiles.shape == (5, 7, 6)
        np.testing.assert_allclose(
            np.asarray(s.point),
            (np.asarray(fan.point) @ lam.T + const[None, :]).T,
            atol=1e-10,
        )
        sub = series_forecast_fan(fan, lam, const=const, series_idx=[2, 4])
        np.testing.assert_allclose(np.asarray(sub.point),
                                   np.asarray(s.point)[[2, 4]], atol=1e-12)
        with pytest.raises(ValueError, match="factor columns"):
            series_forecast_fan(fan, np.zeros((3, 5)))
        with pytest.raises(ValueError, match="const"):
            series_forecast_fan(fan, lam, const=np.zeros(3))
        # scalar const broadcasts instead of crashing
        sc = series_forecast_fan(fan, lam, const=2.0)
        np.testing.assert_allclose(
            np.asarray(sc.point),
            (np.asarray(fan.point) @ lam.T + 2.0).T,
            atol=1e-10,
        )

    def test_fan_sharded_equals_unsharded(self):
        from dynamic_factor_models_tpu.models.favar import bootstrap_forecast_fan

        y = self._ar_panel()
        mesh = make_mesh(8, ("rep",))
        f_sh = bootstrap_forecast_fan(jnp.asarray(y), 1, 0, 299, horizon=4,
                                      n_reps=64, seed=2, mesh=mesh)
        f_1 = bootstrap_forecast_fan(jnp.asarray(y), 1, 0, 299, horizon=4,
                                     n_reps=64, seed=2, mesh=None)
        np.testing.assert_allclose(np.asarray(f_sh.draws), np.asarray(f_1.draws),
                                   atol=1e-10)
        assert "rep" in str(f_sh.draws.sharding)


def test_chol_rep_solver_matches_pinv():
    """The bootstrap's per-replication Cholesky fast path must agree with
    the minimum-norm pinv solve on well-conditioned panels (the ridge is
    ~1e-5 relative, far below estimation noise)."""
    from dynamic_factor_models_tpu.models.favar import _fit_dense_var

    rng = np.random.default_rng(5)
    y = jnp.asarray(
        0.1 * np.cumsum(rng.standard_normal((180, 4)), axis=0)
    )
    b_p, e_p, s_p = _fit_dense_var(y, 2)
    b_c, e_c, s_c = _fit_dense_var(y, 2, solver="chol")
    np.testing.assert_allclose(np.asarray(b_p), np.asarray(b_c), atol=2e-4)
    np.testing.assert_allclose(np.asarray(s_p), np.asarray(s_c), atol=2e-4)


def test_nan_draw_drops_out_of_bands():
    """A pathological replication (NaN draw) must drop out of the band
    quantiles instead of poisoning every element (nanquantile guard)."""
    from dynamic_factor_models_tpu.models.favar import BootstrapIRFs, series_irfs

    rng = np.random.default_rng(6)
    draws = rng.standard_normal((50, 3, 8, 3)).astype(np.float32)
    draws[7] = np.nan  # one dead replication
    boot = BootstrapIRFs(
        point=jnp.asarray(draws[0]),
        draws=jnp.asarray(draws),
        quantiles=jnp.zeros((5, 3, 8, 3)),
        quantile_levels=np.array([0.05, 0.16, 0.5, 0.84, 0.95]),
    )
    lam = rng.standard_normal((6, 3)).astype(np.float32)
    s = series_irfs(boot, jnp.asarray(lam))
    q = np.asarray(s.quantiles)
    assert np.isfinite(q).all()
    assert (np.diff(q, axis=0) >= -1e-6).all()
