"""Serving-layer chaos drills (engine resilience, PR: serving hardening).

Pinned claims:

1. the DFM_FAULTS grammar covers the serving path (`tick_nan@n`,
   `store_io@n`, `slow_req@n`, `engine_crash@n`, with ``+`` = storm);
2. under a fault storm mixing tick_nan / store_io / slow_req across a
   multi-tenant tick/nowcast/refit/scenario workload, 100% of requests
   get a TYPED Response (zero uncaught exceptions), degraded responses
   carry staleness stamps, and post-recovery state matches the
   never-faulted run to <= 1e-10;
3. a single transient fault degrades exactly one tenant (replay buffer
   + degraded nowcasts) and the next clean tick reconciles it; a
   persistent storm opens the per-tenant circuit breaker, which
   half-opens after its cooldown and closes on a successful probe;
4. `store_io` transients are absorbed by bounded retry with
   deterministic backoff; retry exhaustion surfaces a typed
   system-fault envelope with the tick row preserved for replay;
5. `engine_crash@n` kill + restart replays the write-ahead tick journal
   to a BIT-identical FilterState with no caller-side panel; journal
   corruption quarantines the damaged tail and trusts the intact
   prefix;
6. `flush_refits` re-queues failing tenants with a bounded retry count
   and surfaces permanent failures instead of silently dropping them.
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from dynamic_factor_models_tpu.serving.engine import ServingEngine
from dynamic_factor_models_tpu.serving.journal import TickJournal
from dynamic_factor_models_tpu.serving.resilience import (
    CircuitBreaker,
    Response,
    RetryPolicy,
)
from dynamic_factor_models_tpu.serving.store import TenantStore
from dynamic_factor_models_tpu.utils import faults, telemetry

pytestmark = [pytest.mark.serving, pytest.mark.chaos_serving]

# zero backoff keeps the retry drills instant; jitter is deterministic
# anyway (sha256 of key:attempt), so timing never enters the assertions
_POLICY = RetryPolicy(max_retries=2, backoff_base_s=0.0)

T, N = 48, 6


def _panel(seed=0):
    rng = np.random.default_rng(seed)
    f = rng.standard_normal((T, 4)).cumsum(0) * 0.1
    lam = rng.standard_normal((N, 4))
    return f @ lam.T + 0.5 * rng.standard_normal((T, N))


def _engine(store_dir=None, **kw):
    kw.setdefault("retry_policy", _POLICY)
    kw.setdefault("max_em_iter", 5)
    return ServingEngine(store_dir=store_dir, **kw)


# ---------------------------------------------------------------------------
# 1. grammar
# ---------------------------------------------------------------------------


def test_fault_grammar_serving_kinds():
    plan = faults.parse_spec("tick_nan@2;store_io@1+;slow_req@3")
    assert plan.tick_nan == 2 and plan.store_io == 1 and plan.slow_req == 3
    assert plan.persistent == frozenset({"store_io"})
    # one-shot fires at the exact site; a storm fires from the site on
    assert plan.hits("tick_nan", 2) and not plan.hits("tick_nan", 3)
    assert plan.hits("store_io", 1) and plan.hits("store_io", 7)
    # engine_crash defaults to the first request and is never persistent
    assert faults.parse_spec("engine_crash").engine_crash == 1
    with pytest.raises(ValueError, match="persistent"):
        faults.parse_spec("engine_crash@2+")
    with pytest.raises(ValueError, match="needs an iteration"):
        faults.parse_spec("tick_nan")


def test_fault_grammar_pipeline_kinds():
    """`stall_commit@n` / `queue_full@n` ride the same grammar: one-shot
    at a site, ``+`` for a persistent storm, composable with the other
    serving kinds."""
    plan = faults.parse_spec("stall_commit@2;queue_full@1+")
    assert plan.stall_commit == 2 and plan.queue_full == 1
    assert plan.persistent == frozenset({"queue_full"})
    assert plan.hits("stall_commit", 2) and not plan.hits("stall_commit", 3)
    assert plan.hits("queue_full", 1) and plan.hits("queue_full", 9)
    plan = faults.parse_spec("stall_commit@1+;crash_io@4")
    assert plan.persistent == frozenset({"stall_commit"})
    assert plan.crash_io == 4
    with pytest.raises(ValueError, match="needs an iteration"):
        faults.parse_spec("stall_commit")


def test_circuit_breaker_lifecycle():
    br = CircuitBreaker(threshold=3, cooldown=2)
    for _ in range(2):
        br.record_fault()
    assert br.state == "closed"
    br.record_fault()  # third consecutive fault opens
    assert br.state == "open" and br.opens == 1
    assert br.on_request() == "open"        # cooldown 2 -> 1
    assert br.on_request() == "half_open"   # cooldown exhausted: probe
    br.record_fault()                       # failed probe re-opens
    assert br.state == "open" and br.opens == 2
    br.on_request(), br.on_request()
    assert br.state == "half_open"
    br.record_success()
    assert br.state == "closed" and br.consecutive == 0


def test_retry_jitter_deterministic():
    p = RetryPolicy(max_retries=3, backoff_base_s=0.01, backoff_cap_s=0.1)
    assert p.delay_s("k", 1) == p.delay_s("k", 1)
    assert p.delay_s("k", 1) != p.delay_s("other", 1)
    assert 0.005 <= p.delay_s("k", 0) <= 0.01  # half-to-full jitter band
    assert RetryPolicy(backoff_base_s=0.0).delay_s("k", 5) == 0.0


# ---------------------------------------------------------------------------
# 2. the storm: 100% typed responses, staleness stamps, exact recovery
# ---------------------------------------------------------------------------


def test_fault_storm_all_responses_typed(tmp_path):
    rng = np.random.default_rng(1)
    eng = _engine(str(tmp_path / "store"), deadline_s=30.0)
    ref = _engine()  # never-faulted reference
    for i in range(3):
        p = _panel(seed=10 + i)
        eng.register(f"t{i}", p)
        ref.register(f"t{i}", p)

    ticks = {f"t{i}": [rng.standard_normal(N) for _ in range(12)]
             for i in range(3)}
    responses = []
    # storm: every tick poisoned from site 4 on, one store fault, one
    # stalled request — while ALSO serving nowcasts and queueing refits
    with faults.inject("tick_nan@4+;store_io@9;slow_req@11"):
        for k in range(8):
            for tid in ("t0", "t1", "t2"):
                responses.append(eng.handle(
                    {"kind": "tick", "tenant": tid, "x": ticks[tid][k]}
                ))
                responses.append(eng.handle(
                    {"kind": "nowcast", "tenant": tid}
                ))
        responses.append(eng.handle({"kind": "refit", "tenant": "t0"}))
        responses.append(eng.handle(
            {"kind": "nowcast", "tenant": "t0", "horizon": 2}
        ))
    # every single response is a typed envelope; degraded ones stamped
    assert all(isinstance(r, Response) for r in responses)
    failed = [r for r in responses if not r.ok]
    assert failed, "the storm must actually have faulted something"
    assert all(r.error is not None for r in failed)
    assert all(
        r.error.category in ("client_error", "tenant_fault", "system_fault")
        for r in failed
    )
    degraded = [r for r in responses if r.degraded]
    assert degraded and all(r.ticks_behind >= 1 for r in degraded)
    # degraded nowcasts still ANSWER (ok) from last-good state
    assert any(r.ok and r.kind == "nowcast" for r in degraded)

    # storm over: burn any still-open breakers down to their half-open
    # probe with read-only requests (typed fast-fails, rows all safely
    # buffered), then drain the replay buffers with the remaining clean
    # ticks and compare against the never-faulted reference
    for tid in ("t0", "t1", "t2"):
        for _ in range(8):
            if eng._tenants[tid].breaker.state != "open":
                break
            assert eng.handle({"kind": "nowcast", "tenant": tid}).ok
        assert eng._tenants[tid].breaker.state != "open"
    for k in range(8, 12):
        for tid in ("t0", "t1", "t2"):
            r = eng.handle({"kind": "tick", "tenant": tid, "x": ticks[tid][k]})
            assert isinstance(r, Response) and r.ok
    for k in range(12):
        for tid in ("t0", "t1", "t2"):
            assert ref.handle(
                {"kind": "tick", "tenant": tid, "x": ticks[tid][k]}
            ).ok
    for tid in ("t0", "t1", "t2"):
        a, b = eng._tenants[tid], ref._tenants[tid]
        assert not a.replay
        assert int(a.state.t) == int(b.state.t)
        np.testing.assert_allclose(
            np.asarray(a.state.s), np.asarray(b.state.s),
            atol=1e-10, rtol=0,
        )
        nca = eng.handle({"kind": "nowcast", "tenant": tid})
        ncb = ref.handle({"kind": "nowcast", "tenant": tid})
        assert nca.ok and not nca.degraded
        np.testing.assert_allclose(
            np.asarray(nca.result), np.asarray(ncb.result),
            atol=1e-10, rtol=0,
        )


def test_degraded_nowcast_then_lazy_reconcile():
    rng = np.random.default_rng(2)
    eng = _engine()
    ref = _engine()
    p = _panel(seed=3)
    eng.register("a", p)
    ref.register("a", p)
    rows = [rng.standard_normal(N) for _ in range(4)]

    assert eng.handle({"kind": "tick", "tenant": "a", "x": rows[0]}).ok
    with faults.inject("tick_nan@2"):
        bad = eng.handle({"kind": "tick", "tenant": "a", "x": rows[1]})
    assert not bad.ok and bad.error.category == "tenant_fault"
    assert bad.error.code == "nonfinite_state"
    # committed state untouched; nowcast degrades with a staleness stamp
    nc = eng.handle({"kind": "nowcast", "tenant": "a"})
    assert nc.ok and nc.degraded and nc.ticks_behind == 1
    assert int(eng._tenants["a"].state.t) == T + 1
    # next clean tick reconciles the buffered row first
    rec = eng.handle({"kind": "tick", "tenant": "a", "x": rows[2]})
    assert rec.ok and rec.recovered
    for row in rows[:3]:
        assert ref.handle({"kind": "tick", "tenant": "a", "x": row}).ok
    np.testing.assert_allclose(
        np.asarray(eng._tenants["a"].state.s),
        np.asarray(ref._tenants["a"].state.s),
        atol=1e-10, rtol=0,
    )
    assert not eng.handle({"kind": "nowcast", "tenant": "a"}).degraded


def test_breaker_opens_fast_fails_and_recovers():
    rng = np.random.default_rng(4)
    eng = _engine(breaker_threshold=2, breaker_cooldown=2)
    eng.register("a", _panel(seed=5))
    rows = [rng.standard_normal(N) for _ in range(10)]
    with faults.inject("tick_nan@1+"):
        r0 = eng.handle({"kind": "tick", "tenant": "a", "x": rows[0]})
        r1 = eng.handle({"kind": "tick", "tenant": "a", "x": rows[1]})
        r2 = eng.handle({"kind": "tick", "tenant": "a", "x": rows[2]})
    assert r0.error.code == "nonfinite_state" and r0.breaker_state == "closed"
    assert r1.error.code == "nonfinite_state" and r1.breaker_state == "open"
    # r1 reconciled r0's buffered row first (the exact refilter has no
    # tick_nan site), then its own tick was poisoned: 2 rows pending.
    # breaker now open: r2 fast-fails, row buffered, NO compute.
    assert r2.error.code == "breaker_open" and r2.ticks_behind == 2
    # storm over: cooldown burns down to a half-open probe that succeeds
    out = [eng.handle({"kind": "tick", "tenant": "a", "x": rows[3 + i]})
           for i in range(3)]
    probe = next(r for r in out if r.ok)
    assert probe.recovered and probe.breaker_state == "closed"
    assert not eng._tenants["a"].replay
    # every row was folded in: 3 buffered during the storm + 3 after
    assert int(eng._tenants["a"].state.t) - T == 6


# ---------------------------------------------------------------------------
# 3. store_io retries + deadlines
# ---------------------------------------------------------------------------


def test_store_io_transient_absorbed_by_retry(tmp_path):
    rng = np.random.default_rng(6)
    eng = _engine(str(tmp_path / "store"))
    eng.register("a", _panel(seed=7))
    with faults.inject(f"store_io@{eng.store._io_ops + 1}"):
        r = eng.handle({"kind": "tick", "tenant": "a",
                        "x": rng.standard_normal(N)})
    assert r.ok and r.retries == 1  # one injected failure, one retry


def test_store_io_exhaustion_is_typed_and_recoverable(tmp_path):
    rng = np.random.default_rng(8)
    eng = _engine(str(tmp_path / "store"))
    eng.register("a", _panel(seed=9))
    rows = [rng.standard_normal(N) for _ in range(2)]
    with faults.inject(f"store_io@{eng.store._io_ops + 1}+"):
        r = eng.handle({"kind": "tick", "tenant": "a", "x": rows[0]})
    assert not r.ok and r.error.category == "system_fault"
    assert r.error.code == "store_io" and r.ticks_behind == 1
    # storm over: the buffered row reconciles and the journal catches up
    r2 = eng.handle({"kind": "tick", "tenant": "a", "x": rows[1]})
    assert r2.ok and r2.recovered
    assert int(eng._tenants["a"].state.t) == T + 2


def test_slow_req_comes_back_deadline_exceeded():
    rng = np.random.default_rng(10)
    eng = _engine(deadline_s=10.0)
    eng.register("a", _panel(seed=11))
    with faults.inject("slow_req@2"):  # register() is not a request
        ok = eng.handle({"kind": "nowcast", "tenant": "a"})
        stalled = eng.handle({"kind": "nowcast", "tenant": "a"})
    assert ok.ok
    assert not stalled.ok and stalled.error.code == "deadline_exceeded"
    assert stalled.error.category == "system_fault"
    # a stalled TICK preserves its row for replay
    with faults.inject("slow_req@3"):
        r = eng.handle({"kind": "tick", "tenant": "a",
                        "x": rng.standard_normal(N)})
    assert not r.ok and r.error.code == "deadline_exceeded"
    assert r.ticks_behind == 1
    r2 = eng.handle({"kind": "tick", "tenant": "a",
                     "x": rng.standard_normal(N)})
    assert r2.ok and r2.recovered and int(eng._tenants["a"].state.t) == T + 2


# ---------------------------------------------------------------------------
# 4. crash + journal
# ---------------------------------------------------------------------------


def test_engine_crash_restart_replays_journal_bit_identical(tmp_path):
    rng = np.random.default_rng(12)
    d = str(tmp_path / "store")
    eng = _engine(d)
    eng.register("a", _panel(seed=13))
    rows = [rng.standard_normal(N) for _ in range(6)]
    with faults.inject("engine_crash@5"), pytest.raises(faults.SimulatedCrash):
        for row in rows:
            eng.handle({"kind": "tick", "tenant": "a", "x": row})
    # 4 ticks committed before the kill (crash fires at admission of #5)
    s_dead = np.asarray(eng._tenants["a"].state.s).copy()
    assert int(eng._tenants["a"].state.t) == T + 4

    # restart: NO panel re-supplied — snapshot + journal replay only
    eng2 = _engine(d)
    assert eng2.resume("a")
    ten = eng2._tenants["a"]
    assert ten.hist is None
    assert int(ten.state.t) == T + 4
    np.testing.assert_array_equal(np.asarray(ten.state.s), s_dead)

    # the resumed tenant keeps serving AND journaling: tick again, kill
    # again (by just restarting), and the replay still lands exactly
    assert eng2.handle({"kind": "tick", "tenant": "a", "x": rows[4]}).ok
    eng3 = _engine(d)
    assert eng3.resume("a")
    assert int(eng3._tenants["a"].state.t) == T + 5
    np.testing.assert_array_equal(
        np.asarray(eng3._tenants["a"].state.s),
        np.asarray(eng2._tenants["a"].state.s),
    )
    # panel-less tenants answer refit/scenario with a typed envelope
    r = eng3.handle({"kind": "scenario", "tenant": "a",
                     "scenario": {"kind": "stress"}})
    assert not r.ok and r.error.code == "no_history"


def test_journal_corruption_quarantines_damaged_tail(tmp_path):
    rng = np.random.default_rng(14)
    store = TenantStore(str(tmp_path / "store"))
    j = store.journal("a")
    j.reset(5)
    rows = [(5 + i, rng.standard_normal(3), np.ones(3, bool))
            for i in range(3)]
    for t, x, m in rows:
        j.append(t, x, m)
    base, back = j.replay()
    assert base == 5 and len(back) == 3
    np.testing.assert_array_equal(back[1][1], rows[1][1])

    # flip a byte inside the LAST record: sha mismatch drops the tail,
    # trusts the prefix, and preserves the damaged file for forensics
    with open(j.path, "rb") as f:
        lines = f.read().splitlines(keepends=True)
    last = bytearray(lines[-1])
    last[len(last) // 2] ^= 0xFF
    with open(j.path, "wb") as f:
        f.write(b"".join(lines[:-1]) + bytes(last))
    base2, back2 = j.replay()
    assert base2 == 5 and len(back2) == 2
    assert os.path.exists(j.path + ".corrupt")
    # the live journal was rewritten to the intact prefix: stable reads
    base3, back3 = j.replay()
    assert base3 == 5 and len(back3) == 2
    # a torn final append (half a line) is likewise dropped
    with open(j.path, "ab") as f:
        f.write(b'{"t": 99, "dtype": "<f8"')
    _, back4 = j.replay()
    assert len(back4) == 2


def test_tick_journal_is_write_ahead(tmp_path):
    # the journal append happens BEFORE the in-memory commit: a tick
    # whose journal write fails leaves committed state untouched
    rng = np.random.default_rng(15)
    eng = _engine(str(tmp_path / "store"))
    eng.register("a", _panel(seed=16))
    t_before = int(eng._tenants["a"].state.t)
    with faults.inject(f"store_io@{eng.store._io_ops + 1}+"):
        r = eng.handle({"kind": "tick", "tenant": "a",
                        "x": rng.standard_normal(N)})
    assert not r.ok
    assert int(eng._tenants["a"].state.t) == t_before
    # journal headers are created lazily on the first successful append,
    # so a failed first append leaves no file at all (replay → None) —
    # either way, nothing was journaled and nothing was committed
    out = eng.store.journal("a").replay()
    assert out is None or out[1] == []


# ---------------------------------------------------------------------------
# 5. refit retry / permanent failure surfacing
# ---------------------------------------------------------------------------


def test_flush_refits_requeues_then_surfaces_permanent_failures():
    eng = _engine(max_refit_retries=1, max_em_iter=6)
    eng.register("sick", _panel(seed=17))
    with faults.inject("nan_estep@1+"):
        assert eng.handle({"kind": "refit", "tenant": "sick"}).ok
        f1 = eng.flush_refits()
        assert f1.ok and f1.result["sick"].health != 0
        assert f1.info["requeued"] == ["sick"]
        assert f1.info["permanent_failures"] == []
        assert "sick" in eng._refit_queue  # bounded retry: re-queued
        f2 = eng.flush_refits()
        assert f2.info["requeued"] == []
        assert f2.info["permanent_failures"] == ["sick"]
    assert "sick" not in eng._refit_queue  # not silently dropped: SURFACED
    assert telemetry.snapshot()["counters"].get(
        "serving.refit.permanent_failures", 0) >= 1
    # the tenant keeps its previous (finite) fit throughout
    assert np.isfinite(np.asarray(eng._tenants["sick"].params.lam)).all()


# ---------------------------------------------------------------------------
# 6. telemetry: outcome stamps + availability column
# ---------------------------------------------------------------------------


def test_serving_telemetry_outcomes_and_availability(tmp_path):
    sink = str(tmp_path / "run.jsonl")
    rng = np.random.default_rng(18)
    telemetry.enable(sink=sink)
    try:
        eng = _engine()
        eng.register("a", _panel(seed=19))
        assert eng.handle({"kind": "tick", "tenant": "a",
                           "x": rng.standard_normal(N)}).ok
        with faults.inject("tick_nan@2"):
            eng.handle({"kind": "tick", "tenant": "a",
                        "x": rng.standard_normal(N)})
        eng.handle({"kind": "nowcast", "tenant": "a"})   # degraded
        eng.handle({"kind": "tick", "tenant": "a"})       # client error
    finally:
        telemetry.disable()
        # disable() pins the explicit override to False, which would mask
        # DFM_TELEMETRY for every later test in the process; restore the
        # env-driven tri-state.
        telemetry._explicit_enabled = None
    recs = [r for r in telemetry._load_jsonl(sink)
            if r.get("entry") == "serving"]
    outcomes = [r.get("outcome") for r in recs]
    assert "ok" in outcomes and "degraded" in outcomes
    assert "tenant_fault" in outcomes and "client_error" in outcomes
    assert any(r.get("error_kind") == "nonfinite_state" for r in recs)
    assert all("breaker_state" in r and "retries" in r for r in recs)
    table = telemetry.summarize(sink, entry="serving")
    assert "avail" in table
    # 4 requests, 2 answered (ok tick + degraded nowcast) -> 50.0%
    assert "50.0%" in table
