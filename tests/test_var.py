"""VAR estimation, companion form, Cholesky identification, IRFs."""

import jax.numpy as jnp
import numpy as np

from dynamic_factor_models_tpu.models.var import estimate_var, impulse_response


def _simulate_var1(rng, T=4000, ns=2):
    A = np.array([[0.6, 0.2], [-0.1, 0.4]])
    chol = np.array([[1.0, 0.0], [0.5, 0.8]])
    y = np.zeros((T, ns))
    for t in range(1, T):
        y[t] = A @ y[t - 1] + chol @ rng.standard_normal(ns)
    return y, A, chol


def test_var_recovers_coefficients(rng):
    y, A, chol = _simulate_var1(rng)
    res = estimate_var(jnp.asarray(y), nlag=1)
    # betahat rows: [const, lag1]; equation per column
    Ahat = np.asarray(res.betahat[1:, :]).T
    np.testing.assert_allclose(Ahat, A, atol=0.05)
    np.testing.assert_allclose(np.asarray(res.seps), chol @ chol.T, atol=0.1)
    # G is the lower Cholesky factor of seps
    G = np.asarray(res.G)[:2, :2]
    np.testing.assert_allclose(G @ G.T, np.asarray(res.seps), atol=1e-10)
    assert G[0, 1] == 0.0  # lower triangular = recursive identification


def test_var_missing_rows_dropped(rng):
    y, _, _ = _simulate_var1(rng, T=500)
    y_nan = y.copy()
    y_nan[100:110, 0] = np.nan
    res = estimate_var(jnp.asarray(y_nan), nlag=2)
    # 10 missing rows each kill themselves + 2 lagged rows
    assert int(res.T_used) == 500 - 2 - 12
    # residuals NaN at excluded rows
    r = np.asarray(res.resid)
    assert np.isnan(r[100:112]).all()


def test_irf_matches_direct_recursion(rng):
    y, A, chol = _simulate_var1(rng, T=3000)
    res = estimate_var(jnp.asarray(y), nlag=1)
    H = 12
    irfs = np.asarray(impulse_response(res, "all", H))
    assert irfs.shape == (2, H, 2)
    M = np.asarray(res.M)
    Q = np.asarray(res.Q)
    G = np.asarray(res.G)
    for j in range(2):
        x = G[:, j]
        for t in range(H):
            np.testing.assert_allclose(irfs[:, t, j], Q @ x, atol=1e-12)
            x = M @ x
    # scalar path (fixed reference quirk 1)
    single = np.asarray(impulse_response(res, 0, H))
    np.testing.assert_allclose(single, irfs[:, :, 0], atol=0)


def test_var_lag4_companion_shape(rng):
    y, _, _ = _simulate_var1(rng, T=600)
    res = estimate_var(jnp.asarray(y), nlag=4)
    assert res.M.shape == (8, 8)
    assert res.Q.shape == (2, 8)
    assert res.G.shape == (8, 2)
    # companion lower block is the shifted identity
    np.testing.assert_allclose(np.asarray(res.M)[2:, :6], np.eye(6), atol=0)
