"""VAR estimation, companion form, Cholesky identification, IRFs."""

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.var import estimate_var, impulse_response


def _simulate_var1(rng, T=4000, ns=2):
    A = np.array([[0.6, 0.2], [-0.1, 0.4]])
    chol = np.array([[1.0, 0.0], [0.5, 0.8]])
    y = np.zeros((T, ns))
    for t in range(1, T):
        y[t] = A @ y[t - 1] + chol @ rng.standard_normal(ns)
    return y, A, chol


def test_var_recovers_coefficients(rng):
    y, A, chol = _simulate_var1(rng)
    res = estimate_var(jnp.asarray(y), nlag=1)
    # betahat rows: [const, lag1]; equation per column
    Ahat = np.asarray(res.betahat[1:, :]).T
    np.testing.assert_allclose(Ahat, A, atol=0.05)
    np.testing.assert_allclose(np.asarray(res.seps), chol @ chol.T, atol=0.1)
    # G is the lower Cholesky factor of seps
    G = np.asarray(res.G)[:2, :2]
    np.testing.assert_allclose(G @ G.T, np.asarray(res.seps), atol=1e-10)
    assert G[0, 1] == 0.0  # lower triangular = recursive identification


def test_var_missing_rows_dropped(rng):
    y, _, _ = _simulate_var1(rng, T=500)
    y_nan = y.copy()
    y_nan[100:110, 0] = np.nan
    res = estimate_var(jnp.asarray(y_nan), nlag=2)
    # 10 missing rows each kill themselves + 2 lagged rows
    assert int(res.T_used) == 500 - 2 - 12
    # residuals NaN at excluded rows
    r = np.asarray(res.resid)
    assert np.isnan(r[100:112]).all()


def test_irf_matches_direct_recursion(rng):
    y, A, chol = _simulate_var1(rng, T=3000)
    res = estimate_var(jnp.asarray(y), nlag=1)
    H = 12
    irfs = np.asarray(impulse_response(res, "all", H))
    assert irfs.shape == (2, H, 2)
    M = np.asarray(res.M)
    Q = np.asarray(res.Q)
    G = np.asarray(res.G)
    for j in range(2):
        x = G[:, j]
        for t in range(H):
            np.testing.assert_allclose(irfs[:, t, j], Q @ x, atol=1e-12)
            x = M @ x
    # scalar path (fixed reference quirk 1)
    single = np.asarray(impulse_response(res, 0, H))
    np.testing.assert_allclose(single, irfs[:, :, 0], atol=0)


def test_var_lag4_companion_shape(rng):
    y, _, _ = _simulate_var1(rng, T=600)
    res = estimate_var(jnp.asarray(y), nlag=4)
    assert res.M.shape == (8, 8)
    assert res.Q.shape == (2, 8)
    assert res.G.shape == (8, 2)
    # companion lower block is the shifted identity
    np.testing.assert_allclose(np.asarray(res.M)[2:, :6], np.eye(6), atol=0)


def test_long_run_identification_properties(rng):
    # B B' = seps, and the cumulative long-run response C(1) B is
    # lower-triangular (the Blanchard-Quah restriction)
    from dynamic_factor_models_tpu.models.var import (
        estimate_var,
        impulse_response_longrun,
        long_run_impact,
    )

    T, ns = 400, 3
    y = np.zeros((T, ns))
    A1 = np.array([[0.5, 0.1, 0.0], [0.0, 0.4, 0.1], [0.1, 0.0, 0.3]])
    for t in range(1, T):
        y[t] = y[t - 1] @ A1.T + rng.standard_normal(ns)
    var = estimate_var(jnp.asarray(y), 1, 0, T - 1)
    B = np.asarray(long_run_impact(var))
    np.testing.assert_allclose(B @ B.T, np.asarray(var.seps), atol=1e-8)
    b = np.asarray(var.betahat)[1:].T
    C1 = np.linalg.inv(np.eye(ns) - b)
    lr = C1 @ B
    assert np.abs(np.triu(lr, 1)).max() < 1e-8, "C(1)B not lower-triangular"
    # long-run IRFs converge: cumulative response approaches C(1)B
    irfs = np.asarray(impulse_response_longrun(var, 400))
    np.testing.assert_allclose(irfs.sum(axis=1), lr, atol=1e-3)


def test_fevd_shares_sum_to_one(rng):
    from dynamic_factor_models_tpu.models.var import estimate_var, fevd

    T, ns = 300, 3
    y = np.cumsum(rng.standard_normal((T, ns)), axis=0) * 0.05 + rng.standard_normal((T, ns))
    var = estimate_var(jnp.asarray(y), 2, 0, T - 1)
    shares = np.asarray(fevd(var, 12))
    assert shares.shape == (ns, 12, ns)
    np.testing.assert_allclose(shares.sum(axis=2), 1.0, atol=1e-10)
    assert (shares >= -1e-12).all()
    # horizon-1 FEVD under Cholesky: first variable loaded only by shock 1
    np.testing.assert_allclose(shares[0, 0], [1.0, 0.0, 0.0], atol=1e-10)


def test_long_run_impact_noconst_var(rng):
    # layout independence: withconst=False must give the same B as
    # withconst=True on centered data (both read lag blocks from companion M)
    from dynamic_factor_models_tpu.models.var import estimate_var, fevd, long_run_impact

    T, ns = 500, 2
    y = np.zeros((T, ns))
    A1 = np.array([[0.5, 0.1], [0.0, 0.4]])
    for t in range(1, T):
        y[t] = y[t - 1] @ A1.T + rng.standard_normal(ns)
    y = y - y.mean(axis=0)
    B_c = np.asarray(long_run_impact(estimate_var(jnp.asarray(y), 1, withconst=True)))
    B_nc = np.asarray(long_run_impact(estimate_var(jnp.asarray(y), 1, withconst=False)))
    np.testing.assert_allclose(B_c, B_nc, atol=5e-3)
    # fevd under long-run identification still sums to one
    var = estimate_var(jnp.asarray(y), 1)
    sh = np.asarray(fevd(var, 8, impact=long_run_impact(var)))
    np.testing.assert_allclose(sh.sum(axis=2), 1.0, atol=1e-10)


class TestVARToolkit:
    """Lag selection, generalized IRFs, Granger causality (beyond ref)."""

    @staticmethod
    def _var2_panel(T=400, seed=0):
        rng = np.random.default_rng(seed)
        A1 = np.array([[0.5, 0.1], [0.0, 0.4]])
        A2 = np.array([[0.2, 0.0], [0.1, 0.15]])
        y = np.zeros((T, 2))
        for t in range(2, T):
            y[t] = A1 @ y[t - 1] + A2 @ y[t - 2] + rng.standard_normal(2)
        return y

    def test_lag_selection_recovers_true_order(self):
        from dynamic_factor_models_tpu.models.var import select_var_lag

        sel = select_var_lag(jnp.asarray(self._var2_panel()), max_lag=5)
        assert sel.best["bic"] == 2, f"BIC picked {sel.best['bic']}"
        assert sel.best["hq"] == 2
        assert sel.best["aic"] >= 2  # AIC may overfit, never underfit here
        assert sel.aic.shape == (5,)
        with pytest.raises(ValueError, match="max_lag"):
            select_var_lag(jnp.asarray(self._var2_panel()), max_lag=0)

    def test_lag_selection_common_sample_with_missing(self):
        """Interior NaNs knock out different rows per candidate order; the
        criteria must still be computed on one common sample (the selector
        asserts identical T_eff internally) and still find the truth."""
        from dynamic_factor_models_tpu.models.var import select_var_lag

        y = self._var2_panel(T=500)
        y[100, 0] = np.nan
        y[300, 1] = np.nan
        sel = select_var_lag(jnp.asarray(y), max_lag=4)
        assert sel.best["bic"] == 2

    def test_generalized_irf_identities(self):
        from dynamic_factor_models_tpu.models.var import (
            estimate_var,
            generalized_irf,
            impulse_response,
        )

        y = self._var2_panel()
        var = estimate_var(jnp.asarray(y), 2)
        girf = generalized_irf(var, T=12)
        chol = impulse_response(var, "all", 12)
        assert girf.shape == chol.shape == (2, 12, 2)
        # exact identity: the GIRF of the FIRST variable equals the
        # recursive IRF (chol(Sigma)[:,0] = Sigma e_1 / sqrt(sigma_11))
        np.testing.assert_allclose(
            np.asarray(girf[:, :, 0]), np.asarray(chol[:, :, 0]), atol=1e-10
        )
        # with an exactly diagonal Sigma, every GIRF equals the Cholesky IRF
        from dynamic_factor_models_tpu.models.var import (
            VARResults,
            companion_matrices,
        )

        seps_d = jnp.asarray(np.diag([1.3, 0.7]))
        M, Q, G = companion_matrices(var.betahat, seps_d, 2)
        var_d = VARResults(var.betahat, var.resid, seps_d, M, Q, G,
                           var.T_used, 2)
        np.testing.assert_allclose(
            np.asarray(generalized_irf(var_d, 8)),
            np.asarray(impulse_response(var_d, "all", 8)),
            atol=1e-10,
        )

    def test_granger_causality_detects_direction(self):
        from dynamic_factor_models_tpu.models.var import granger_causality

        rng = np.random.default_rng(3)
        T = 500
        y = np.zeros((T, 2))
        for t in range(1, T):
            y[t, 0] = 0.5 * y[t - 1, 0] + 0.4 * y[t - 1, 1] + rng.standard_normal()
            y[t, 1] = 0.5 * y[t - 1, 1] + rng.standard_normal()
        gc_10 = granger_causality(jnp.asarray(y), caused=0, causing=1, nlag=2)
        gc_01 = granger_causality(jnp.asarray(y), caused=1, causing=0, nlag=2)
        assert gc_10.pvalue < 1e-4, f"true causality missed: p={gc_10.pvalue}"
        assert gc_01.pvalue > 0.05, f"spurious causality: p={gc_01.pvalue}"
        assert gc_10.df == 2
        # survival-function path keeps tail information (no 1-cdf
        # cancellation to exactly 0.0)
        assert gc_10.pvalue > 0.0

    def test_granger_validation(self):
        from dynamic_factor_models_tpu.models.var import granger_causality

        y = jnp.asarray(self._var2_panel(T=100))
        with pytest.raises(ValueError, match="disjoint"):
            granger_causality(y, caused=0, causing=0, nlag=1)
        with pytest.raises(ValueError, match="out of range"):
            granger_causality(y, caused=0, causing=5, nlag=1)
