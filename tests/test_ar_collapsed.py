"""Exactness of the quasi-differenced (collapsed) BM-AR path.

`em_step_ar_qd` runs EM for the kappa = 0 AR(1)-idiosyncratic model with a
state of r*max(p,2) factor lags only — the N idio states are eliminated by
exact quasi-differencing (z_it = x_it - phi_i x_{i,t-1}, unit Jacobian).
`em_step_ar_dense0` is the dense parity oracle: the IDENTICAL kappa = 0
model filtered in covariance form with the full r*max(p,2)+N state.  The
two must agree to float-reorder error (the ISSUE-10 acceptance pins 1e-8;
observed agreement is ~1e-13) — any drift means the collapse stopped being
an algebraic identity.
"""

import warnings

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models import ssm_ar as M

pytestmark = pytest.mark.large_n

TOL = 1e-8  # acceptance bound; observed ~1e-13 in f64


def _ar_dgp(rng, T=40, N=24, r=2, p=1):
    """Ragged contiguous-run panel (heads/tails missing, one dead series)
    from a factor + AR(1)-idio DGP, plus a perturbed-truth init."""
    phi_true = rng.uniform(-0.6, 0.8, N)
    lam_true = rng.normal(size=(N, r))
    A1 = 0.6 * np.eye(r)
    f = np.zeros((T, r))
    for t in range(1, T):
        f[t] = f[t - 1] @ A1.T + rng.normal(size=r) * 0.5
    e = np.zeros((T, N))
    for t in range(1, T):
        e[t] = phi_true * e[t - 1] + rng.normal(size=N) * 0.4
    x = f @ lam_true.T + e
    mask = np.ones((T, N), bool)
    for i in range(N):
        head, tail = rng.integers(0, 5), rng.integers(0, 5)
        mask[:head, i] = False
        if tail:
            mask[T - tail:, i] = False
    mask[:, 3] = False  # one fully-missing series
    xz = jnp.asarray(np.where(mask, x, 0.0))
    m = jnp.asarray(mask)
    params = M.SSMARParams(
        lam=jnp.asarray(lam_true + 0.1 * rng.normal(size=(N, r))),
        phi=jnp.asarray(
            np.clip(phi_true + 0.1 * rng.normal(size=N), -0.9, 0.9)
        ),
        sigv2=jnp.full((N,), 0.3),
        A=jnp.asarray(A1)[None],
        Q=0.25 * jnp.eye(r),
    )
    return params, xz, m


def test_qd_em_matches_dense_oracle(rng):
    """Params AND loglik of the collapsed EM step track the dense kappa=0
    oracle through 5 iterations at <= 1e-8 (the tentpole acceptance)."""
    params, xz, m = _ar_dgp(rng)
    qd = M.compute_qd_stats(xz, m)
    assert M.qd_mask_supported(np.asarray(m))
    pq = pd = params
    for _ in range(5):
        pq2, llq = M.em_step_ar_qd(pq, xz, qd)
        pd2, lld = M.em_step_ar_dense0(pd, xz, m, qd)
        assert abs(float(llq) - float(lld)) <= TOL * (1 + abs(float(lld)))
        for a, b in zip(pq2, pd2):
            np.testing.assert_allclose(a, b, atol=TOL)
        pq, pd = pq2, pd2


def test_qd_em_loglik_monotone(rng):
    params, xz, m = _ar_dgp(rng)
    qd = M.compute_qd_stats(xz, m)
    lls, pp = [], params
    for _ in range(12):
        pp, ll = M.em_step_ar_qd(pp, xz, qd)
        lls.append(float(ll))
    assert all(np.isfinite(lls))
    assert all(b >= a - 1e-9 for a, b in zip(lls, lls[1:]))


def test_qd_smoothed_factors_and_idio_match_dense(rng):
    """The O(T N) idio recovery (observed residual + phi-decay fill) equals
    the dense oracle's smoothed idio STATES, and the factor blocks agree —
    the E-step moments the M-step consumes are the same numbers."""
    params, xz, m = _ar_dgp(rng)
    qd = M.compute_qd_stats(xz, m)
    pq = params
    for _ in range(3):
        pq, _ = M.em_step_ar_qd(pq, xz, qd)
    pqg = M._guard_params_qd(pq)
    mm, cc, pm, pc, _ = M._filter_ar_qd(pqg, xz, qd)
    Tmq, _ = M._qd_companion(pqg)
    s_sm_q, _, _ = M._rts_scan(Tmq, mm, cc, pm, pc)
    idio_q = M.idio_moments_qd(pqg, xz, qd, s_sm_q)
    md, cd, pmd, pcd, _ = M._filter_ar_dense0(pqg, xz, m)
    Tmd, _, _, _ = M._dense0_system(pqg)
    s_sm_d, _, _ = M._rts_scan(Tmd, md, cd, pmd, pcd)
    rpt = pqg.r * max(pqg.p, 2)
    np.testing.assert_allclose(
        s_sm_q[:, : pqg.r], s_sm_d[:, : pqg.r], atol=TOL
    )
    np.testing.assert_allclose(idio_q, s_sm_d[:, rpt:], atol=TOL)


def test_qd_mask_class_gate():
    """Contiguous runs (ragged heads/tails) are in; interior gaps are out."""
    m = np.ones((10, 3), bool)
    m[:4, 0] = False
    m[8:, 1] = False
    assert M.qd_mask_supported(m)
    m[5, 2] = False  # interior gap
    assert not M.qd_mask_supported(m)
    assert M.qd_mask_supported(np.zeros((10, 3), bool))  # all-missing ok


def test_collapsed_method_falls_back_on_interior_gaps(rng):
    """estimate_dfm_em_ar(method='collapsed') on an interior-gap panel must
    warn and produce the dense path's answer, not silently mis-filter."""
    from dynamic_factor_models_tpu.models.dfm import DFMConfig

    T, N = 80, 8
    x = np.cumsum(rng.normal(size=(T, N)), axis=0) * 0.1 + rng.normal(
        size=(T, N)
    )
    x[40, 2] = np.nan  # interior gap -> outside the QD mask class
    inclcode = np.ones(N, np.int64)
    cfg = DFMConfig(nfac_u=1, n_factorlag=1)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        res = M.estimate_dfm_em_ar(
            x, inclcode, 0, T - 1, cfg, max_em_iter=3, method="collapsed"
        )
    assert any("falling back" in str(wi.message) for wi in w)
    assert np.isfinite(np.asarray(res.loglik_path)).all()


def test_dense_budget_guard_raises_with_pointer(monkeypatch):
    """The dense-path memory estimate fails LOUDLY against DFM_MEM_BUDGET
    and names the collapsed escape hatch."""
    monkeypatch.setenv("DFM_MEM_BUDGET", "1000000")  # 1 MB
    with pytest.raises(MemoryError) as ei:
        M.check_dense_ar_budget(512, 10_000, 4, 1, itemsize=4)
    msg = str(ei.value)
    assert "DFM_MEM_BUDGET" in msg and "collapsed" in msg


def test_dense_budget_guard_passes_small(monkeypatch):
    monkeypatch.delenv("DFM_MEM_BUDGET", raising=False)
    M.check_dense_ar_budget(128, 64, 2, 1, itemsize=8)  # no raise


def test_estimate_method_validated():
    from dynamic_factor_models_tpu.models.dfm import DFMConfig

    with pytest.raises(ValueError, match="method"):
        M.estimate_dfm_em_ar(
            np.zeros((10, 3)), np.ones(3, np.int64), 0, 9,
            DFMConfig(nfac_u=1, n_factorlag=1), method="nope",
        )
