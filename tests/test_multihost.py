"""Multi-host scale-out (PR-15): the process-spanning ``("dcn", "ici")``
data mesh and the hierarchical ICI+DCN Gram reduction.

A real multi-process runtime can't live inside the tier-1 pytest process
(jax.distributed.initialize is once-per-process), so the coverage splits:

* tier-1 proxy (this file, unmarked/`multidevice`): hosts=2 forced onto the
  single-process 8-device CPU platform — the SAME 2-D mesh, tuple
  PartitionSpec flattening, and hierarchical reduce as the real two-host
  program, minus the OS-process boundary.  Pins hierarchical == flat ring
  at 1e-12 and every step factory's hosts=2 output against its flat twin.
* the real thing (`slow` + `multihost`): two OS processes joined by
  `jax.distributed.initialize` run the sharded estimators end-to-end via
  tests/_dist_worker.py mode "em" — <= 1e-10 parity vs the single-process
  reference asserted in-worker, bit-identical SPMD results pinned across
  processes by digest equality here.
"""

import os
import socket
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models import emcore, mixed_freq, ssm, ssm_ar
from dynamic_factor_models_tpu.models import transforms as tfm
from dynamic_factor_models_tpu.models.mixed_freq import MixedFreqParams
from dynamic_factor_models_tpu.models.ssm import compute_panel_stats
from dynamic_factor_models_tpu.ops.pallas_gram import (
    hierarchical_allreduce,
    ring_allreduce,
)
from dynamic_factor_models_tpu.parallel.mesh import P, data_mesh

from test_sharding import _max_leaf_diff, _mf_panel, _panel, _prep_padded

PARITY_ATOL = 1e-10  # acceptance bar for step/estimator outputs
REDUCE_ATOL = 1e-12  # acceptance bar for the raw reduction itself

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_dist_worker.py")


# ---------------------------------------------------------------------------
# 1. mesh construction
# ---------------------------------------------------------------------------


@pytest.mark.multidevice
def test_data_mesh_hosts_topology():
    flat = data_mesh(8)
    assert flat.axis_names == ("data",)
    assert flat.devices.shape == (8,)
    two = data_mesh(8, hosts=2)
    assert two.axis_names == ("dcn", "ici")
    assert two.devices.shape == (2, 4)
    # same device set, row-major: the "ici" rows partition the flat order
    assert [d.id for d in two.devices.ravel()] == [d.id for d in flat.devices]
    # hosts=0/None resolve to process_count() -> 1 here -> the flat mesh
    assert data_mesh(8, hosts=0).axis_names == ("data",)
    assert data_mesh(8, hosts=None).axis_names == ("data",)


def test_data_mesh_hosts_validation():
    with pytest.raises(ValueError, match="divide evenly"):
        data_mesh(8, hosts=3)
    with pytest.raises(ValueError, match="devices"):
        data_mesh(2 * jax.device_count(), hosts=2)


# ---------------------------------------------------------------------------
# 2. the reduction itself: hierarchical (ICI ring + DCN psum) == flat ring
# ---------------------------------------------------------------------------


@pytest.mark.multidevice
def test_hierarchical_reduce_matches_flat_ring():
    """The tier-1 pin behind the two-host program: reducing within the
    "ici" axis then once across "dcn" must equal the flat 8-way ring at
    <= 1e-12 (reduction order differs, bitwise identity is not promised)."""
    from jax.experimental.shard_map import shard_map

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((8, 33)))

    flat = jax.jit(
        shard_map(
            lambda a: ring_allreduce(a, "data", 8),
            mesh=data_mesh(8),
            in_specs=P("data", None),
            out_specs=P("data", None),
            check_rep=False,
        )
    )(x)
    hier = jax.jit(
        shard_map(
            lambda a: hierarchical_allreduce(a, "ici", "dcn", 4),
            mesh=data_mesh(8, hosts=2),
            in_specs=P(("dcn", "ici"), None),
            out_specs=P(("dcn", "ici"), None),
            check_rep=False,
        )
    )(x)
    want = np.asarray(x).sum(0)
    for got in (np.asarray(flat), np.asarray(hier)):
        assert got.shape == (8, 33)  # every shard holds the full sum
        np.testing.assert_allclose(got, np.broadcast_to(want, got.shape),
                                   atol=REDUCE_ATOL, rtol=0)
    np.testing.assert_allclose(np.asarray(flat), np.asarray(hier),
                               atol=REDUCE_ATOL, rtol=0)


# ---------------------------------------------------------------------------
# 3. step factories: hosts=2 output == flat single-host output
# ---------------------------------------------------------------------------


@pytest.mark.multidevice
def test_ssm_sharded_step_hosts2_matches_flat():
    params, xz, mask, stats = _prep_padded(60, 37, 8, r=3, p=2, seed=3)
    p1, ll1 = ssm._sharded_step_for(8)(params, xz, mask, stats)
    p2, ll2 = ssm._sharded_step_for(8, hosts=2)(params, xz, mask, stats)
    assert abs(float(ll1) - float(ll2)) <= PARITY_ATOL
    assert _max_leaf_diff(p1, p2) <= PARITY_ATOL


@pytest.mark.multidevice
def test_ar_sharded_step_hosts2_matches_flat():
    rng = np.random.default_rng(13)
    T, N, r = 80, 24, 2  # N divides evenly: no padding in the way
    phi_true = rng.uniform(-0.5, 0.7, N)
    lam = rng.standard_normal((N, r))
    f = np.zeros((T, r))
    e = np.zeros((T, N))
    for t in range(1, T):
        f[t] = 0.6 * f[t - 1] + 0.5 * rng.standard_normal(r)
        e[t] = phi_true * e[t - 1] + 0.4 * rng.standard_normal(N)
    x = f @ lam.T + e
    for i in range(6):  # contiguous-prefix missingness (the QD mask class)
        x[: int(rng.integers(1, 6)), i] = np.nan
    m = ~np.isnan(x)
    xz = jnp.asarray(np.where(m, x, 0.0))
    params = ssm_ar.SSMARParams(
        lam=jnp.asarray(0.3 * rng.standard_normal((N, r))),
        phi=jnp.zeros(N),
        sigv2=jnp.ones(N),
        A=0.5 * jnp.eye(r)[None],
        Q=jnp.eye(r),
    )
    qd = ssm_ar.compute_qd_stats(xz, jnp.asarray(m))
    p1, ll1 = emcore._ar_sharded_step_for(8)(params, xz, qd)
    p2, ll2 = emcore._ar_sharded_step_for(8, hosts=2)(params, xz, qd)
    assert abs(float(ll1) - float(ll2)) <= PARITY_ATOL
    assert _max_leaf_diff(p1, p2) <= PARITY_ATOL


def _mf_step_inputs(T=48, N=16, r=2, p=5, seed=21):
    rng = np.random.default_rng(seed)
    n_q = 4
    is_q = np.zeros(N, bool)
    is_q[-n_q:] = True
    agg = np.zeros((N, 5))
    agg[~is_q, 0] = 1.0
    agg[is_q] = np.array([1.0, 2.0, 3.0, 2.0, 1.0]) / 3.0
    x = rng.standard_normal((T, N))
    x[rng.random((T, N)) < 0.2] = np.nan
    for j in np.nonzero(is_q)[0]:
        x[np.arange(T) % 3 != 2, j] = np.nan
    A = np.concatenate(
        [(0.6 * np.eye(r))[None], 0.05 * rng.standard_normal((p - 1, r, r))]
    )
    params = MixedFreqParams(
        lam=jnp.asarray(rng.standard_normal((N, r))),
        R=jnp.asarray(0.2 + rng.random(N)),
        A=jnp.asarray(A),
        Q=jnp.eye(r),
        agg=jnp.asarray(agg),
    )
    m = ~np.isnan(x)
    xz = jnp.asarray(np.nan_to_num(x))
    mask = jnp.asarray(m)
    return params, xz, mask, compute_panel_stats(xz, mask)


@pytest.mark.multidevice
def test_mf_sharded_step_hosts2_matches_flat():
    params, xz, mask, stats = _mf_step_inputs()
    p0, ll0 = mixed_freq.em_step_mf_stats(params, xz, mask, stats)
    p1, ll1 = mixed_freq._mf_sharded_step_for(8)(params, xz, mask, stats)
    p2, ll2 = mixed_freq._mf_sharded_step_for(8, hosts=2)(params, xz, mask, stats)
    # flat sharded == sequential (the lifted-refusal exactness argument)
    assert abs(float(ll0) - float(ll1)) <= PARITY_ATOL
    assert _max_leaf_diff(p0, p1) <= PARITY_ATOL
    # hierarchical == flat
    assert abs(float(ll1) - float(ll2)) <= PARITY_ATOL
    assert _max_leaf_diff(p1, p2) <= PARITY_ATOL


# ---------------------------------------------------------------------------
# 4. dispatcher identity, transform plumbing, telemetry rendering
# ---------------------------------------------------------------------------


@pytest.mark.multidevice
def test_dispatcher_identity():
    # one cache entry no matter how hosts=0 is spelled (the `is`-identity
    # contract the transform-stack pins rely on)
    assert ssm._sharded_step_for(2) is ssm._sharded_step_for(2, 0)
    assert ssm._sharded_step_for(2) is ssm._sharded_step_for(2, hosts=0)
    assert emcore._ar_sharded_step_for(2) is emcore._ar_sharded_step_for(2, 0)
    assert (
        mixed_freq._mf_sharded_step_for(2)
        is mixed_freq._mf_sharded_step_for(2, hosts=0)
    )
    # hosts>1 is a DIFFERENT program and must never alias the flat cache
    # entry (its AOT-registry name carries the _h suffix)
    assert ssm._sharded_step_for(8, 2) is not ssm._sharded_step_for(8)
    assert ssm._sharded_step_for(8, 2) is ssm._sharded_step_for(8, hosts=2)


@pytest.mark.multidevice
def test_transform_stack_carries_hosts():
    assert tfm.shard(8).args == (8, 0)
    assert tfm.shard(8, 2).args == (8, 2)
    res = tfm.resolve(tfm.Stack("ssm", (tfm.shard(8, 2),)))
    assert res.step is ssm._sharded_step_for(8, 2)
    assert res.hosts == 2 and res.n_shards == 8
    res_ar = tfm.resolve(tfm.Stack("ar", (tfm.collapse(), tfm.shard(8, 2))))
    assert res_ar.step is emcore._ar_sharded_step_for(8, 2)
    res_mf = tfm.resolve(tfm.Stack("mf", (tfm.shard(8),)))
    assert res_mf.step is mixed_freq._mf_sharded_step_for(8)
    assert res_mf.fallback_step is mixed_freq.em_step_mf_stats
    # hosts=0 resolution leaves the single-host identity intact
    assert (
        tfm.resolve(tfm.Stack("ssm", (tfm.shard(8),))).step
        is ssm._sharded_step_for(8)
    )


def test_dev_str_renders_process_mesh():
    from dynamic_factor_models_tpu.utils import telemetry

    assert telemetry._dev_str({"sharded": True, "mesh_shape": [2, 4]}) == "2x4"
    assert telemetry._dev_str({"sharded": True, "mesh_shape": [8]}) == "8"
    assert telemetry._dev_str({}) == "-"


# ---------------------------------------------------------------------------
# 5. mixed-frequency padding: aggregation rows exactly inert
# ---------------------------------------------------------------------------


@pytest.mark.multidevice
def test_mf_padded_aggregation_rows_inert():
    """Padding a mixed-frequency panel appends monthly rows (agg = e0,
    zero loadings, all-False mask): their H5 block-rows are identically
    zero, so through three EM steps the padded run's real slice matches
    the unpadded run and the padded loadings stay exactly dark — even
    under the period-3 quarterly mask cycle of the real series."""
    T, N, Np = 48, 10, 16
    x, is_q = _mf_panel(T, N, n_quarterly=4)
    r = 2
    rng = np.random.default_rng(31)
    m = ~np.isnan(x)
    xz = jnp.asarray(np.where(m, x, 0.0))
    mask = jnp.asarray(m)
    agg = np.zeros((N, 5))
    agg[~is_q, 0] = 1.0
    agg[is_q] = np.array([1.0, 2.0, 3.0, 2.0, 1.0]) / 3.0
    lam = 0.3 * rng.standard_normal((N, r))
    A = np.concatenate([(0.5 * np.eye(r))[None], np.zeros((4, r, r))])
    params = MixedFreqParams(
        lam=jnp.asarray(lam), R=jnp.ones(N), A=jnp.asarray(A),
        Q=jnp.eye(r), agg=jnp.asarray(agg),
    )
    # the estimator's inert-padding recipe, applied by hand
    pad = Np - N
    xz_p = jnp.concatenate([xz, jnp.zeros((T, pad))], axis=1)
    mask_p = jnp.concatenate([mask, jnp.zeros((T, pad), bool)], axis=1)
    agg_p = np.zeros((Np, 5))
    agg_p[:N] = agg
    agg_p[N:, 0] = 1.0  # padded rows are monthly: plain e0 aggregation
    params_p = MixedFreqParams(
        lam=jnp.concatenate([params.lam, jnp.zeros((pad, r))]),
        R=jnp.concatenate([params.R, jnp.ones(pad)]),
        A=params.A, Q=params.Q, agg=jnp.asarray(agg_p),
    )
    stats = compute_panel_stats(xz, mask)
    stats_p = compute_panel_stats(xz_p, mask_p)
    # all-False rows weigh zero: the padded panel's total obs count is
    # unchanged, so the M-step denominators agree exactly
    assert float(stats.n_obs.sum()) == float(stats_p.n_obs.sum())
    p1, p2 = params, params_p
    for _ in range(3):
        p1, ll1 = mixed_freq.em_step_mf_stats(p1, xz, mask, stats)
        p2, ll2 = mixed_freq.em_step_mf_stats(p2, xz_p, mask_p, stats_p)
        np.testing.assert_array_equal(np.asarray(p2.lam[N:]), 0.0)
        assert abs(float(ll1) - float(ll2)) <= PARITY_ATOL
    assert _max_leaf_diff(
        (p1.lam, p1.R, p1.A, p1.Q), (p2.lam[:N], p2.R[:N], p2.A, p2.Q)
    ) <= PARITY_ATOL


# ---------------------------------------------------------------------------
# 6. the real thing: two OS processes, one global mesh
# ---------------------------------------------------------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
@pytest.mark.multihost
def test_two_process_sharded_em_parity(tmp_path):
    """Two workers (4 virtual CPU devices each) join one 8-device runtime
    and run estimate_dfm_em / estimate_dfm_em_ar(method="collapsed") with
    n_shards=8 over the ("dcn", "ici") mesh.  Each worker asserts <= 1e-10
    parity against its local single-process reference; here we assert both
    exited clean and reported BIT-IDENTICAL results (SPMD digest)."""
    port, nproc = _free_port(), 2
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    logs = [(tmp_path / f"w{i}.out", tmp_path / f"w{i}.err") for i in range(nproc)]
    procs = []
    try:
        for i in range(nproc):
            with open(logs[i][0], "w") as out, open(logs[i][1], "w") as err:
                procs.append(
                    subprocess.Popen(
                        [sys.executable, _WORKER, str(i), str(nproc),
                         str(port), "em"],
                        stdout=out,
                        stderr=err,
                        env=env,
                    )
                )
        deadline = time.monotonic() + 600  # hard timeout for the drill
        while any(p.poll() is None for p in procs):
            if any(p.poll() not in (None, 0) for p in procs):
                break  # a dead worker strands the other at the barrier
            if time.monotonic() > deadline:
                break
            time.sleep(0.5)
    finally:
        # orphan cleanup: never leak a worker past the test, pass or fail
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()

    dumps = [
        f"worker {i}: rc={p.returncode}\n{logs[i][0].read_text()}"
        f"\n{logs[i][1].read_text()[-2000:]}"
        for i, p in enumerate(procs)
    ]
    assert all(p.returncode == 0 for p in procs), "\n\n".join(dumps)
    results = sorted(
        line
        for o, _ in logs
        for line in o.read_text().splitlines()
        if line.startswith("RESULT")
    )
    assert len(results) == nproc, "\n\n".join(dumps)
    payloads = {r.split("pid=")[1].split(" ", 1)[1] for r in results}
    assert len(payloads) == 1, f"processes disagree: {results}"
    assert "digest=" in results[0]
