"""estimate_factor_batch vs sequential estimate_factor equivalence.

The batch pads heterogeneous fits (different r, different sample windows) to
one static shape with inert zero factor columns / zero-weight rows; these
tests pin that the padding is exactly inert: each element reproduces its own
sequential fit.
"""

import dataclasses

import numpy as np
import pytest

from dynamic_factor_models_tpu.models.dfm import (
    DFMConfig,
    estimate_factor,
    estimate_factor_batch,
)
from dynamic_factor_models_tpu.models.selection import estimate_factor_numbers


def _align(a, b):
    s = np.sign(np.nansum(a * b, axis=0))
    s[s == 0] = 1.0
    return b * s


def test_batch_matches_serial_over_r(dataset_real):
    ds = dataset_real
    cfg = DFMConfig(tol=1e-8)
    rs = (1, 2, 4)
    panels = [(ds.bpdata, ds.inclcode, 2, 223, r) for r in rs]
    batch = estimate_factor_batch(panels, cfg)
    for i, r in enumerate(rs):
        f_s, fes_s = estimate_factor(
            ds.bpdata, ds.inclcode, 2, 223, dataclasses.replace(cfg, nfac_u=r)
        )
        np.testing.assert_allclose(
            float(batch.ssr[i]), float(fes_s.ssr), rtol=1e-6
        )
        fb = np.asarray(batch.factor[i])[:, :r]
        fs = np.asarray(f_s)
        np.testing.assert_allclose(
            np.nan_to_num(_align(fs, fb)), np.nan_to_num(fs), atol=1e-4
        )
        # padded columns are reported NaN
        assert np.isnan(np.asarray(batch.factor[i])[:, r:]).all()
        np.testing.assert_allclose(
            np.asarray(batch.R2[i]), np.asarray(fes_s.R2), atol=1e-6, equal_nan=True
        )


@pytest.mark.slow
def test_batch_matches_serial_over_windows(dataset_real):
    ds = dataset_real
    cfg = DFMConfig(tol=1e-8)
    windows = [(2, 223), (2, 103), (104, 223)]
    panels = [(ds.bpdata, ds.inclcode, a, b, 2) for a, b in windows]
    batch = estimate_factor_batch(panels, cfg)
    for i, (a, b) in enumerate(windows):
        f_s, fes_s = estimate_factor(
            ds.bpdata, ds.inclcode, a, b, dataclasses.replace(cfg, nfac_u=2)
        )
        np.testing.assert_allclose(
            float(batch.ssr[i]), float(fes_s.ssr), rtol=1e-6
        )
        fb = np.asarray(batch.factor[i])[:, :2]
        fs = np.asarray(f_s)
        # identical NaN pattern outside the window
        assert np.array_equal(np.isnan(fb), np.isnan(fs))
        np.testing.assert_allclose(
            np.nan_to_num(_align(fs, fb)), np.nan_to_num(fs), atol=1e-4
        )


def test_batch_sharded_over_mesh_matches(dataset_real):
    """Batch axis sharded over the 8-device CPU mesh == unsharded results
    (SURVEY section 3.3 fan-out; no cross-chip traffic until the gather)."""
    import jax

    from dynamic_factor_models_tpu.parallel.mesh import make_mesh

    ds = dataset_real
    cfg = DFMConfig(tol=1e-8)
    panels = [(ds.bpdata, ds.inclcode, 2, 223, r) for r in (1, 2, 3)]
    mesh = make_mesh(len(jax.devices()), axis_names=("batch",))
    sharded = estimate_factor_batch(panels, cfg, mesh=mesh)  # B=3 pads to 8
    plain = estimate_factor_batch(panels, cfg)
    assert sharded.factor.shape == plain.factor.shape
    np.testing.assert_allclose(
        np.asarray(sharded.ssr), np.asarray(plain.ssr), rtol=1e-10
    )
    np.testing.assert_allclose(
        np.nan_to_num(np.asarray(sharded.factor)),
        np.nan_to_num(np.asarray(plain.factor)),
        atol=1e-8,
    )


def test_factor_numbers_batched_consistency(dataset_real):
    """The batched sweep reproduces its own serial building blocks: static
    ICp2 values decrease-then-increase consistently and AW diag aligns with
    re-running amengual_watson_test serially for one r."""
    from dynamic_factor_models_tpu.models.selection import amengual_watson_test

    ds = dataset_real
    cfg = DFMConfig(tol=1e-8)
    stats = estimate_factor_numbers(ds.bpdata, ds.inclcode, 2, 223, cfg, 3)
    f_s, _ = estimate_factor(
        ds.bpdata, ds.inclcode, 2, 223, dataclasses.replace(cfg, nfac_u=3)
    )
    aw_s, _, _ = amengual_watson_test(
        ds.bpdata, ds.inclcode, f_s, 2, 223, cfg, 3
    )
    np.testing.assert_allclose(stats.aw_icp[:3, 2], aw_s, atol=2e-3)


def test_rolling_factor_estimates(dataset_real):
    """Rolling windows: each batch element equals its own serial fit, and
    the per-window trace R2 trajectory is sane on the real panel."""
    from dynamic_factor_models_tpu.models.dfm import rolling_factor_estimates

    import pytest

    ds = dataset_real
    cfg = DFMConfig(tol=1e-8)
    roll = rolling_factor_estimates(
        ds.bpdata, ds.inclcode, window=80, nfac=1, config=cfg,
        step=24, initperiod=2, lastperiod=223,
    )
    n_windows = len(roll.starts)
    assert n_windows == (223 - 80 + 2 - 2) // 24 + 1
    assert roll.batch.factor.shape[1] == 80  # sliced to the window
    tr = 1.0 - np.asarray(roll.batch.ssr) / np.asarray(roll.batch.tss)
    assert np.isfinite(tr).all() and (tr > 0.2).all() and (tr < 0.9).all()
    # spot-check one window against the serial estimator
    i = n_windows // 2
    s = int(roll.starts[i])
    f_s, fes_s = estimate_factor(
        ds.bpdata, ds.inclcode, s, s + 79, dataclasses.replace(cfg, nfac_u=1)
    )
    np.testing.assert_allclose(
        float(roll.batch.ssr[i]), float(fes_s.ssr), rtol=1e-6
    )
    assert np.isfinite(np.asarray(roll.batch.factor[i])[:, 0]).all()
    with pytest.raises(ValueError, match="window"):
        rolling_factor_estimates(
            ds.bpdata, ds.inclcode, window=300, nfac=1, config=cfg
        )
    with pytest.raises(ValueError, match="invalid rows"):
        rolling_factor_estimates(
            ds.bpdata, ds.inclcode, window=80, nfac=1, config=cfg,
            lastperiod=500,
        )
