"""Golden-value tests against the reference's committed notebook outputs
(BASELINE.md; tolerance 1e-3 on printed values)."""

import numpy as np
import pytest

from dynamic_factor_models_tpu.models.dfm import (
    DFMConfig,
    compute_series,
    estimate_dfm,
    estimate_factor,
)
from dynamic_factor_models_tpu.models.selection import (
    ahn_horenstein_er,
    estimate_factor_numbers,
)

WINDOW = (2, 223)  # (1959Q3, 2014Q4), 0-based


@pytest.fixture(scope="module")
def fnes_real(dataset_real):
    return estimate_factor_numbers(
        dataset_real.bpdata, dataset_real.inclcode, *WINDOW, DFMConfig(), 5,
        dynamic=False,
    )


def test_table2a_trace_r2(fnes_real):
    np.testing.assert_allclose(
        fnes_real.trace_r2, [0.385, 0.489, 0.533, 0.564, 0.594], atol=1e-3
    )


def test_table2a_bai_ng(fnes_real):
    np.testing.assert_allclose(
        fnes_real.bn_icp, [-0.398, -0.493, -0.494, -0.475, -0.458], atol=1e-3
    )


def test_table2a_ahn_horenstein(fnes_real):
    er = ahn_horenstein_er(fnes_real.marginal_r2)
    np.testing.assert_allclose(er[:4], [3.739, 2.340, 1.384, 1.059], atol=1e-3)


@pytest.mark.slow
def test_table2b_and_2c_all_panel(dataset_all):
    fnes = estimate_factor_numbers(
        dataset_all.bpdata, dataset_all.inclcode, *WINDOW, DFMConfig(), 4,
        dynamic=True,
    )
    np.testing.assert_allclose(
        fnes.trace_r2, [0.215, 0.296, 0.358, 0.398], atol=1e-3
    )
    np.testing.assert_allclose(
        fnes.bn_icp, [-0.184, -0.233, -0.266, -0.271], atol=1e-3
    )
    # Table 2(C) Amengual-Watson dynamic-factor ICp
    assert abs(fnes.aw_icp[0, 0] - (-0.098)) < 1e-3
    assert abs(fnes.aw_icp[3, 3] - (-0.077)) < 1e-3


def test_full_pipeline_benchmark_config(dataset_real):
    """estimate! with the driver's benchmark hyperparameters (cells 15-19)."""
    res = estimate_dfm(dataset_real.bpdata, dataset_real.inclcode, *WINDOW, DFMConfig(nfac_u=1))
    # factor defined exactly on the window
    f = np.asarray(res.factor[:, 0])
    assert np.isnan(f[:2]).all() and not np.isnan(f[2:]).any()
    # loadings/r2 defined for nearly all series; r2 in [0, 1]
    r2 = np.asarray(res.r2)
    assert np.isfinite(r2).sum() > 80
    assert np.nanmax(r2) <= 1.0 + 1e-12
    # factor VAR is stationary: companion eigenvalues inside unit circle
    ev = np.linalg.eigvals(np.asarray(res.var.M))
    assert np.abs(ev).max() < 1.0
    # idiosyncratic AR: finite where loading was estimated
    assert np.isfinite(np.asarray(res.uar_ser)[np.isfinite(r2)]).all()
    # common component of GDP correlates strongly with GDP growth
    i = dataset_real.bpnamevec.index("GDPC96")
    cc = np.asarray(compute_series(res, i))
    y = np.asarray(dataset_real.bpdata[:, i])
    m = np.isfinite(cc) & np.isfinite(y)
    corr = np.corrcoef(cc[m], y[m])[0, 1]
    assert corr > 0.7


def test_single_iteration_no_r2(dataset_real):
    """estimate_factor!(dfmm, 1, false) path used by the Figure-6 sweep."""
    _, fes = estimate_factor(
        dataset_real.bpdata, dataset_real.inclcode, *WINDOW, DFMConfig(nfac_u=2),
        max_iter=1, compute_R2=False,
    )
    assert int(fes.n_iter) == 1
    assert np.isnan(np.asarray(fes.R2)).all()
    assert float(fes.ssr) > 0


def test_estimation_window_subsample(dataset_real):
    """Pre-84 window runs and produces a sane trace R^2 (Figure 3 loop)."""
    _, fes = estimate_factor(
        dataset_real.bpdata, dataset_real.inclcode, 2, 99, DFMConfig(nfac_u=1)
    )
    tr = 1 - float(fes.ssr) / float(fes.tss)
    assert 0.3 < tr < 0.7


class TestOnatskiED:
    def test_recovers_true_factor_count(self):
        from dynamic_factor_models_tpu.models.selection import onatski_ed

        # seed chosen for clean recovery: the ED rule's max-j scan can
        # over-pick on unlucky noise-eigenvalue gaps (inherent sampling
        # behavior of the estimator, not a bug)
        rng = np.random.default_rng(1)
        for r_true in (1, 3, 5):
            T, N = 300, 40
            f = rng.standard_normal((T, r_true))
            lam = rng.standard_normal((N, r_true)) * 1.5
            x = f @ lam.T + rng.standard_normal((T, N))
            r_hat, evals, delta = onatski_ed(x, rmax=10)
            assert r_hat == r_true
            assert delta > 0 and (np.diff(evals) <= 1e-10).all()

    def test_handles_missing_and_real_panel(self, dataset_real):
        from dynamic_factor_models_tpu.models.selection import onatski_ed

        x = np.asarray(dataset_real.bpdata)[:, np.asarray(dataset_real.inclcode) == 1]
        r_hat, evals, delta = onatski_ed(x[2:224], rmax=10)
        # the Stock-Watson panel has a small handful of strong factors
        assert 1 <= r_hat <= 6
        assert np.isfinite(evals).all()

    def test_rmax_validation(self):
        from dynamic_factor_models_tpu.models.selection import onatski_ed

        with pytest.raises(ValueError, match="rmax"):
            onatski_ed(np.random.default_rng(0).standard_normal((50, 10)), rmax=10)


class TestSelectionVariants:
    """Bai-Ng ICp1/ICp3 and Ahn-Horenstein GR alongside the reference's
    ICp2/ER, on a synthetic panel with a known factor count."""

    @staticmethod
    def _panel(r_true=3, T=250, N=60, seed=0):
        rng = np.random.default_rng(seed)
        f = rng.standard_normal((T, r_true))
        lam = 1.5 * rng.standard_normal((N, r_true))
        return f @ lam.T + rng.standard_normal((T, N))

    def test_all_icp_variants_recover_true_r(self):
        import jax.numpy as jnp

        from dynamic_factor_models_tpu.models import (
            DFMConfig,
            bai_ng_criterion,
            bai_ng_criterion_variant,
            estimate_factor,
        )

        x = self._panel()
        ones = np.ones(x.shape[1], np.int64)
        vals = {v: [] for v in ("icp1", "icp2", "icp3")}
        for r in range(1, 7):
            _, fes = estimate_factor(
                jnp.asarray(x), ones, 0, x.shape[0] - 1,
                DFMConfig(nfac_u=r, tol=1e-8, max_iter=2000),
            )
            for v in vals:
                vals[v].append(float(bai_ng_criterion_variant(fes, r, v)))
            # the icp2 variant IS the reference criterion
            np.testing.assert_allclose(
                vals["icp2"][-1], float(bai_ng_criterion(fes, r)), rtol=1e-12
            )
        for v, seq in vals.items():
            assert int(np.argmin(seq)) + 1 == 3, f"{v} picked {np.argmin(seq)+1}"
        with pytest.raises(ValueError, match="variant"):
            bai_ng_criterion_variant(fes, 1, "icp9")

    def test_growth_ratio_agrees_with_er_on_sharp_structure(self):
        from dynamic_factor_models_tpu.models import (
            ahn_horenstein_er,
            ahn_horenstein_gr,
        )

        x = self._panel()
        xz = (x - x.mean(0)) / x.std(0)
        ev = np.linalg.eigvalsh(xz.T @ xz / x.shape[0])[::-1]
        shares = ev / ev.sum()
        er = ahn_horenstein_er(shares)
        gr = ahn_horenstein_gr(shares)
        assert gr.shape == (shares.size - 1,)  # GR_1..GR_{R-1}
        assert int(np.argmax(er[:8])) + 1 == 3
        assert int(np.nanargmax(gr)) + 1 == 3
        # full-spectrum input: only the terminal V=0 step may be NaN
        assert np.isfinite(gr[:-1]).all()
        # truncated marginal shares (the estimate_factor_numbers shape)
        # stay finite everywhere: V keeps the idiosyncratic remainder
        gr_trunc = ahn_horenstein_gr(shares[:10])
        assert np.isfinite(gr_trunc).all()
        assert int(np.nanargmax(gr_trunc)) + 1 == 3

    def test_sweep_bundle_exposes_variants(self):
        import jax.numpy as jnp

        from dynamic_factor_models_tpu.models import (
            DFMConfig,
            estimate_factor_numbers,
        )

        x = self._panel()
        stats = estimate_factor_numbers(
            jnp.asarray(x), np.ones(x.shape[1], np.int64), 0, x.shape[0] - 1,
            DFMConfig(tol=1e-8, max_iter=2000), max_nfac=6, dynamic=False,
        )
        np.testing.assert_allclose(stats.icp("icp2"), stats.bn_icp, rtol=1e-10)
        assert int(np.argmin(stats.icp("icp1"))) + 1 == 3
        gr = stats.growth_ratio
        assert np.isfinite(gr).all()  # truncated sweep: V keeps the idio tail
        assert int(np.nanargmax(gr)) + 1 == 3
