"""Multi-tenant serving layer (dynamic_factor_models_tpu/serving/).

Pinned claims:

1. the O(1) constant-gain online tick reproduces the full refilter's
   filtered means to 1e-10 over 50 ticks, for both the complete (d=1)
   and the mixed-frequency period-3 observation patterns, and its
   compiled HLO carries no factorization op and no dependence on the
   sample length T;
2. batched multi-tenant EM (one vmapped while-loop over B stacked
   same-bucket panels) matches the sequential per-tenant loop to 1e-10,
   and a fault-injected divergent tenant is rolled back and frozen
   without perturbing its bucket-mates (bit-identical results);
3. pad_panel / pad_ssm_params padding is EXACTLY inert: a padded
   tenant's EM iterates match its unpadded run to ~1e-13 — the
   exactness batched multi-tenant EM relies on;
4. the tenant store inherits utils/checkpoint's digest verification: a
   corrupted archive (including a DFM_FAULTS=ckpt_corrupt injection) is
   quarantined and reported missing, other tenants unaffected, and
   `checkpoint.list_entries` enumerates only live archives.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamic_factor_models_tpu.models import mixed_freq as mf
from dynamic_factor_models_tpu.models.emloop import run_em_loop, run_em_loop_batched
from dynamic_factor_models_tpu.models.ssm import (
    SSMParams,
    compute_panel_stats,
    em_step_stats,
    kalman_filter,
)
from dynamic_factor_models_tpu.serving import (
    FilterState,
    ServingEngine,
    derive_serving_model,
    derive_serving_model_mf,
    nowcast,
    online_tick,
)
from dynamic_factor_models_tpu.serving.batch import (
    RefitRequest,
    refit_batch,
    refit_sequential,
)
from dynamic_factor_models_tpu.serving.online import _tick
from dynamic_factor_models_tpu.serving.store import (
    TenantState,
    TenantStore,
    template_state,
)
from dynamic_factor_models_tpu.utils import faults
from dynamic_factor_models_tpu.utils.checkpoint import list_entries, save_pytree
from dynamic_factor_models_tpu.utils.compile import (
    bucket_shape,
    pad_panel,
    pad_ssm_params,
    unpad_ssm_params,
)

pytestmark = pytest.mark.serving


def _params(rng, N, r, p, a=0.5):
    lam = jnp.asarray(rng.standard_normal((N, r)))
    A = jnp.zeros((p, r, r)).at[0].set(a * jnp.eye(r))
    return SSMParams(lam, jnp.ones(N), A, jnp.eye(r))


def _panel(rng, params, T, N):
    r = params.lam.shape[1]
    f = rng.standard_normal((T, r)) * 0.5
    return np.asarray(
        f @ np.asarray(params.lam).T + 0.5 * rng.standard_normal((T, N))
    )


# ---------------------------------------------------------------------------
# 1. online tick parity + O(1) structure
# ---------------------------------------------------------------------------


def test_online_tick_matches_full_refilter():
    rng = np.random.default_rng(0)
    T, N, r, p = 160, 16, 2, 2
    params = _params(rng, N, r, p)
    x = _panel(rng, params, T, N)

    filt = kalman_filter(params, x)
    model = derive_serving_model(params)
    assert model.period == 1

    t0 = T - 50
    st = FilterState(
        s=jnp.asarray(filt.means[t0 - 1]), t=jnp.asarray(t0, jnp.int32)
    )
    for t in range(t0, T):
        st = online_tick(model, st, x[t], np.isfinite(x[t]))
        np.testing.assert_allclose(
            np.asarray(st.s), np.asarray(filt.means[t]), atol=1e-10, rtol=0
        )
    assert int(st.t) == T


def test_online_tick_matches_full_refilter_mf():
    rng = np.random.default_rng(3)
    T, N, r, p, n_q = 240, 24, 2, 5, 6
    lam = jnp.asarray(rng.standard_normal((N, r)))
    A = jnp.zeros((p, r, r)).at[0].set(0.4 * jnp.eye(r))
    agg = jnp.zeros((N, 5)).at[:, 0].set(1.0)
    agg = agg.at[:n_q].set(jnp.asarray([1.0, 2.0, 3.0, 2.0, 1.0]) / 3.0)
    params = mf.MixedFreqParams(lam, jnp.ones(N), A, jnp.eye(r), agg)

    f = rng.standard_normal((T, r)) * 0.5
    x = np.asarray(f @ np.asarray(lam).T + 0.5 * rng.standard_normal((T, N)))
    mask = np.ones((T, N), bool)
    mask[:, :n_q] = (np.arange(T) % 3 == 2)[:, None]  # quarter-end months
    xz = jnp.asarray(np.where(mask, x, 0.0))
    m = jnp.asarray(mask)

    means, *_ = mf._filter_mf(params, xz, m)
    model = derive_serving_model_mf(params)
    assert model.period == 3

    t0 = T - 50
    st = FilterState(
        s=jnp.asarray(means[t0 - 1]), t=jnp.asarray(t0, jnp.int32)
    )
    for t in range(t0, T):
        # absolute clock keeps the phase aligned: t % 3 picks the gain
        st = online_tick(model, st, xz[t], m[t])
        np.testing.assert_allclose(
            np.asarray(st.s), np.asarray(means[t]), atol=1e-10, rtol=0
        )


def test_tick_hlo_factorization_free_and_T_independent():
    rng = np.random.default_rng(1)
    N, r, p = 16, 2, 2
    params = _params(rng, N, r, p)
    model = derive_serving_model(params)
    st = FilterState(s=jnp.zeros(r * p), t=jnp.asarray(0, jnp.int32))
    x_t = jnp.zeros(N)
    m_t = jnp.ones(N, bool)

    lowered = _tick.lower(model, st, x_t, m_t)
    hlo = lowered.as_text()
    assert "cholesky" not in hlo and "triangular" not in hlo
    compiled = lowered.compile().as_text().lower()
    for op in ("potrf", "trsm", "cholesky", "triangular"):
        assert op not in compiled, f"{op} in compiled tick"

    # O(1) in T: the tick's traced program is a function of the MODEL
    # shapes only — nothing of the history length T appears in the
    # lowering inputs, so per-tick cost cannot depend on T; re-lowering
    # is byte-stable
    hlo2 = _tick.lower(model, st, x_t, m_t).as_text()
    assert hlo == hlo2


def test_nowcast_readout_and_padding_inert():
    rng = np.random.default_rng(2)
    N, r, p = 8, 2, 2
    params = _params(rng, N, r, p)
    model = derive_serving_model(params)
    s = jnp.asarray(rng.standard_normal(r * p))
    st = FilterState(s=s, t=jnp.asarray(7, jnp.int32))
    np.testing.assert_allclose(
        np.asarray(nowcast(model, st)),
        np.asarray(params.lam @ s[:r]),
        atol=1e-12,
    )
    # horizon iterates the companion transition
    np.testing.assert_allclose(
        np.asarray(nowcast(model, st, horizon=2)),
        np.asarray(params.lam @ (model.Tm @ (model.Tm @ s))[:r]),
        atol=1e-12,
    )
    # padded rows read out exactly zero and contribute nothing to ticks
    padded = derive_serving_model(params, n_pad=16)
    out = np.asarray(nowcast(padded, st))
    assert out.shape == (16,)
    np.testing.assert_allclose(out[N:], 0.0, atol=0)
    x_t = rng.standard_normal(16)
    mask_t = np.zeros(16, bool)
    mask_t[:N] = True
    st_pad = online_tick(padded, st, x_t, mask_t)
    st_raw = online_tick(model, st, x_t[:N], mask_t[:N])
    np.testing.assert_allclose(
        np.asarray(st_pad.s), np.asarray(st_raw.s), atol=1e-14
    )


# ---------------------------------------------------------------------------
# 2. pad_panel exactness (satellite: the invariant batching relies on)
# ---------------------------------------------------------------------------


def test_pad_panel_em_fixed_point_exact():
    rng = np.random.default_rng(4)
    # same (T, N, r, p) as _refit_requests so the padded-bucket EM
    # program compiles once for this whole module
    T, N, r, p = 100, 12, 2, 2
    true = _params(rng, N, r, p)
    x = jnp.asarray(_panel(rng, true, T, N))
    mask = jnp.ones((T, N), bool)
    start = _params(rng, N, r, p, a=0.3)._replace(
        lam=0.1 * jnp.asarray(rng.standard_normal((N, r)))
    )

    t_pad, n_pad = bucket_shape(T, N)
    assert (t_pad, n_pad) == (128, 16)
    xp, mp, tw = pad_panel(x, mask, t_pad, n_pad)
    # padded entries are mask-false with zero values
    assert not bool(mp[:, N:].any()) and not bool(mp[T:].any())
    assert not bool(xp[:, N:].any()) and not bool(xp[T:].any())
    np.testing.assert_array_equal(np.asarray(tw), (np.arange(t_pad) < T))

    n_it = 30  # matches the batched tests' max_em_iter static
    stats = compute_panel_stats(x, mask)
    res = run_em_loop(em_step_stats, start, (x, mask, stats), 0.0, n_it)
    stats_p = compute_panel_stats(xp, mp)._replace(tw=tw)
    res_p = run_em_loop(
        em_step_stats, pad_ssm_params(start, n_pad), (xp, mp, stats_p),
        0.0, n_it,
    )
    un = unpad_ssm_params(res_p.params, N)
    for a, b in zip(jax.tree.leaves(res.params), jax.tree.leaves(un)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-13, rtol=0
        )
    np.testing.assert_allclose(
        res.loglik_path[:n_it], res_p.loglik_path[:n_it], atol=1e-9, rtol=0
    )


# ---------------------------------------------------------------------------
# 3. batched EM: parity + one-bad-tenant isolation
# ---------------------------------------------------------------------------


def _refit_requests(rng, B, T=100, N=12, r=2, p=2):
    reqs = []
    for i in range(B):
        true = _params(rng, N, r, p)
        x = jnp.asarray(_panel(rng, true, T, N))
        start = _params(rng, N, r, p, a=0.3)._replace(
            lam=0.1 * jnp.asarray(rng.standard_normal((N, r)))
        )
        reqs.append(
            RefitRequest(f"tenant{i}", x, jnp.ones((T, N), bool), start)
        )
    return reqs


def test_batched_em_matches_sequential():
    rng = np.random.default_rng(5)
    reqs = _refit_requests(rng, 4)
    rb = refit_batch(reqs, tol=1e-6, max_em_iter=30)
    rs = refit_sequential(reqs, tol=1e-6, max_em_iter=30)
    assert [r.tenant_id for r in rb] == [r.tenant_id for r in rs]
    for a, b in zip(rb, rs):
        assert (a.n_iter, a.converged, a.health) == (
            b.n_iter, b.converged, b.health,
        )
        for la, lb in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)):
            np.testing.assert_allclose(
                np.asarray(la), np.asarray(lb), atol=1e-10, rtol=0
            )
        assert abs(a.loglik - b.loglik) <= 1e-8 * (1 + abs(b.loglik))


@pytest.mark.chaos
def test_batched_one_bad_tenant_isolated():
    rng = np.random.default_rng(6)
    reqs = _refit_requests(rng, 4)
    # max_em_iter matches test_batched_em_matches_sequential so the
    # fault-free batched program is a jit-cache hit
    clean = refit_batch(reqs, tol=1e-6, max_em_iter=30)
    with faults.inject("nan_estep@3"):
        faulty = refit_batch(reqs, tol=1e-6, max_em_iter=30)
    # tenant 0 tripped at iteration 3: rolled back to its last-good
    # iterate and frozen (health=nonfinite, n_iter stuck before the trip)
    assert faulty[0].health == 1
    assert faulty[0].n_iter == 2
    assert not faulty[0].converged
    assert all(np.isfinite(v).all() for v in jax.tree.leaves(faulty[0].params))
    # bucket-mates are BIT-identical to the fault-free batch
    for c, f in zip(clean[1:], faulty[1:]):
        assert f.health == 0
        assert (f.n_iter, f.converged) == (c.n_iter, c.converged)
        for lc, lf in zip(jax.tree.leaves(c.params), jax.tree.leaves(f.params)):
            np.testing.assert_array_equal(np.asarray(lc), np.asarray(lf))


def test_run_em_loop_batched_validates():
    rng = np.random.default_rng(7)
    reqs = _refit_requests(rng, 2, T=40, N=6)
    with pytest.raises(ValueError, match="max_em_iter"):
        run_em_loop_batched(
            em_step_stats,
            jax.tree.map(lambda *xs: jnp.stack(xs), *[r.params for r in reqs]),
            (),
            1e-6,
            0,
        )


# ---------------------------------------------------------------------------
# 4. tenant store + list_entries
# ---------------------------------------------------------------------------


def _tenant_state(rng, N=6, r=2, p=2):
    return TenantState(
        params=_params(rng, N, r, p),
        s=jnp.asarray(rng.standard_normal(r * p)),
        t=jnp.asarray(40, jnp.int32),
        r=jnp.asarray(r, jnp.int32),
        p=jnp.asarray(p, jnp.int32),
    )


def test_store_roundtrip_and_listing(tmp_path):
    rng = np.random.default_rng(8)
    store = TenantStore(str(tmp_path / "store"))
    like = template_state(6, 2, 2)
    assert store.list() == []
    st_a, st_b = _tenant_state(rng), _tenant_state(rng)
    store.save("a", st_a)
    store.save("b", st_b)
    assert store.list() == ["a", "b"]
    back = store.load("a", like)
    for x, y in zip(jax.tree.leaves(st_a), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    assert store.load("never-saved", like) is None
    with pytest.raises(ValueError, match="invalid tenant id"):
        store.save("../evil", st_a)
    assert store.delete("b") and store.list() == ["a"]


def test_store_corrupt_archive_quarantined(tmp_path):
    rng = np.random.default_rng(9)
    d = str(tmp_path / "store")
    store = TenantStore(d)
    like = template_state(6, 2, 2)
    store.save("good", _tenant_state(rng))
    store.save("bad", _tenant_state(rng))
    with open(os.path.join(d, "bad.npz"), "r+b") as f:
        f.truncate(10)
    assert store.load("bad", like) is None
    assert os.path.exists(os.path.join(d, "bad.npz.corrupt"))
    assert not os.path.exists(os.path.join(d, "bad.npz"))
    assert store.list() == ["good"]  # quarantine is invisible to listing
    assert store.load("good", like) is not None


@pytest.mark.chaos
def test_store_survives_ckpt_corrupt_injection(tmp_path):
    rng = np.random.default_rng(10)
    store = TenantStore(str(tmp_path / "store"))
    like = template_state(6, 2, 2)
    st = _tenant_state(rng)
    store.save("t0", st)
    with faults.inject("ckpt_corrupt@2"):
        s2 = TenantStore(store.directory)
        s2.save("t1", st)
        s2.save("t2", st)  # second save through s2 is damaged
        s2.save("t3", st)
    assert store.load("t2", like) is None  # quarantined on load
    for tid in ("t0", "t1", "t3"):  # neighbors unaffected
        assert store.load(tid, like) is not None
    assert store.list() == ["t0", "t1", "t3"]


def test_list_entries_excludes_temp_and_corrupt(tmp_path):
    d = str(tmp_path / "ck")
    assert list_entries(d) == []  # missing dir is an empty store
    os.makedirs(d)
    save_pytree(os.path.join(d, "x.npz"), {"a": jnp.arange(3)})
    save_pytree(os.path.join(d, "y.npz"), {"a": jnp.arange(3)})
    os.rename(os.path.join(d, "y.npz"), os.path.join(d, "y.npz.corrupt"))
    # in-flight temp from the atomic-write protocol
    with open(os.path.join(d, "z.npz.tmp.123.abcd.npz"), "wb") as f:
        f.write(b"partial")
    assert list_entries(d) == ["x"]


# ---------------------------------------------------------------------------
# 5. engine request loop + CLI
# ---------------------------------------------------------------------------


def test_engine_requests(tmp_path):
    rng = np.random.default_rng(11)
    # same (T, N, r, p) as the CLI-demo test: register/tick/derive
    # programs compile once for both
    T, N, r, p = 48, 6, 4, 4
    params = _params(rng, N, r, p)
    x = _panel(rng, params, T, N)
    eng = ServingEngine(store_dir=str(tmp_path / "store"), max_em_iter=8)
    eng.register("acme", x, params=params)
    assert eng.tenant_ids() == ["acme"]

    st0 = eng.handle({"kind": "tick", "tenant": "acme",
                      "x": rng.standard_normal(N)})
    assert st0.ok and not st0.degraded
    assert int(st0.result.t) == T + 1
    nc = eng.handle({"kind": "nowcast", "tenant": "acme"})
    assert nc.ok and np.asarray(nc.result).shape == (N,)
    qr = eng.handle({"kind": "refit", "tenant": "acme"})
    assert qr.ok and qr.result == 0
    flush = eng.flush_refits()
    assert flush.ok
    assert flush.result["acme"].health == 0
    assert flush.result["acme"].n_iter == 8
    assert flush.info["installed"] == 1
    assert not flush.info["permanent_failures"]
    assert eng.flush_refits().result == {}  # queue drained

    # errors come back as TYPED envelopes naming the offending field,
    # never raw exceptions out of the request loop
    resp = eng.handle({"kind": "tick", "tenant": "nope", "x": np.zeros(N)})
    assert not resp.ok and resp.error.category == "client_error"
    assert resp.error.code == "unknown_tenant"
    assert resp.error.field == "tenant"
    resp = eng.handle({"kind": "frobnicate", "tenant": "acme"})
    assert not resp.ok and resp.error.code == "unknown_kind"
    resp = eng.handle({"kind": "tick", "tenant": "acme"})
    assert not resp.ok and resp.error.code == "missing_field"
    assert resp.error.field == "x"
    resp = eng.handle({"kind": "tick", "tenant": "acme", "x": [1.0, 2.0]})
    assert not resp.ok and resp.error.code == "bad_shape"
    assert resp.error.field == "x"

    # store-backed resume re-derives serving state from persisted params
    eng2 = ServingEngine(store_dir=str(tmp_path / "store"))
    assert eng2.resume("acme", x)
    assert not eng2.resume("ghost", x)
    nc2 = eng2.handle({"kind": "nowcast", "tenant": "acme"})
    assert nc2.ok and np.asarray(nc2.result).shape == (N,)


def test_resume_non_default_factor_counts(tmp_path):
    # regression: resume() used to guess template_state(N, 4, 4) — (r, p)
    # now persist in TenantState, so an r=2 tenant round-trips exactly
    rng = np.random.default_rng(31)
    T, N, r, p = 48, 6, 2, 3
    params = _params(rng, N, r, p)
    x = _panel(rng, params, T, N)
    eng = ServingEngine(store_dir=str(tmp_path / "store"))
    eng.register("acme", x, params=params)
    s0 = np.asarray(eng._tenants["acme"].state.s)
    assert s0.shape == (r * p,)

    eng2 = ServingEngine(store_dir=str(tmp_path / "store"))
    assert eng2.resume("acme", x)
    ten = eng2._tenants["acme"]
    assert ten.params.lam.shape == (N, r) and ten.params.A.shape == (p, r, r)
    np.testing.assert_array_equal(np.asarray(ten.state.s), s0)
    # panel-less (crash-restart) path restores the same state from the
    # snapshot alone, for the same non-default (r, p)
    eng3 = ServingEngine(store_dir=str(tmp_path / "store"))
    assert eng3.resume("acme")
    np.testing.assert_array_equal(
        np.asarray(eng3._tenants["acme"].state.s), s0
    )


def test_tick_history_amortized_append():
    # perf regression: the tick path used np.vstack (O(T) copy per tick);
    # the append buffer must realloc only logarithmically often and hand
    # out zero-copy views of the live prefix
    from dynamic_factor_models_tpu.serving.engine import _History

    rng = np.random.default_rng(32)
    h = _History(rng.standard_normal((40, 6)), np.ones((40, 6), bool))
    for _ in range(1000):
        h.append(np.zeros(6), np.ones(6, bool))
    assert h.n == 1040 and h.x.shape == (1040, 6)
    assert h.reallocs <= int(np.ceil(np.log2(1040 / 40))) + 1
    assert h.x.base is h._x and h.mask.base is h._mask


def test_serve_cli_demo(capsys):
    import json as _json

    from dynamic_factor_models_tpu.serving.engine import main

    rc = main(["--tenants", "2", "--T", "48", "--N", "6",
               "--ticks", "2", "--max-em-iter", "3"])
    assert rc == 0
    phases = [_json.loads(ln) for ln in
              capsys.readouterr().out.strip().splitlines()]
    assert [p["phase"] for p in phases] == [
        "register", "ticks", "refit", "scenario"
    ]
    assert set(phases[2]["results"]) == {"tenant0", "tenant1"}
    assert phases[3]["fan_shape"] == [2, 6, 6]
