"""Worker supervision (PR 19): liveness detection, automatic respawn,
and tenant failover for the sharded serving router.

Acceptance pins:

1. `kill_worker` at EVERY client-RPC site of a tick stream yields 100%
   typed ``worker_unavailable`` responses (no hang, no raw exception)
   and — with client retry-until-ok — a final state BIT-IDENTICAL to a
   never-killed control router, double-kill included;
2. in-worker stage kills (`engine_crash` at admission, `crash_io` at
   every journal/snapshot/commit I/O site) surface typed and respect
   the PR 13 ``acked <= recovered <= acked + deaths`` journal bound;
3. survivors never miss a tick, gang refits abort-and-retry instead of
   wedging, `recover()` quarantines planted partition junk and
   proceeds;
4. `close()` is idempotent and deadline-bounded with terminate→kill
   escalation on a wedged worker; ``__exit__`` never raises;
5. RunRecords from router-routed requests carry `worker_id`, and
   `summarize` renders the per-worker lifecycle glyph column.

Process-backend drills (SIGKILL, real stall, wedged close) are marked
slow; the inproc matrix rides tier-1.
"""

import glob
import json
import os
import time

import numpy as np
import pytest

from dynamic_factor_models_tpu.serving.engine import ServingEngine
from dynamic_factor_models_tpu.serving.resilience import (
    SYSTEM_FAULT,
    WORKER_DEAD,
    WORKER_HEALTHY,
    WORKER_RECOVERING,
    RetryPolicy,
    WorkerSupervisor,
)
from dynamic_factor_models_tpu.serving.router import (
    TenantRouter,
    _sanitize,
    worker_of,
)
from dynamic_factor_models_tpu.serving.store import worker_partition
from dynamic_factor_models_tpu.utils import faults, flight, telemetry

pytestmark = [pytest.mark.serving, pytest.mark.chaos_serving]

_POLICY = RetryPolicy(max_retries=2, backoff_base_s=0.0)
T, N = 48, 6

# hash layout for n_workers=2 (worker_of is frozen by the on-disk
# partition format, so these are stable): worker 0 owns c0/c1/seed,
# worker 1 owns c2/c3
_W0 = ("c0", "c1")
_W1 = ("c2", "c3")


@pytest.fixture(autouse=True)
def _clean_obs():
    telemetry.disable()
    flight.reset()
    yield
    telemetry.disable()
    telemetry._explicit_enabled = None
    flight.reset()


def _panel(seed=0):
    rng = np.random.default_rng(seed)
    f = rng.standard_normal((T, 4)).cumsum(0) * 0.1
    lam = rng.standard_normal((N, 4))
    return f @ lam.T + 0.5 * rng.standard_normal((T, N))


def _router(store_dir, **kw):
    kw.setdefault(
        "engine_kwargs", {"max_em_iter": 3, "retry_policy": _POLICY}
    )
    return TenantRouter(2, store_dir=store_dir, backend="inproc", **kw)


def _register(rt, ids=_W0 + _W1, seed=7):
    rt.register_seed("seed", _panel(seed))
    for tid in ids:
        rt.register_shared(tid, "seed")


def _tick(tid, row):
    return {"kind": "tick", "tenant": tid, "x": row}


def _stream(ids, ticks=2, seed=11):
    rows = np.random.default_rng(seed).standard_normal(
        (ticks, len(ids), N)
    )
    return [
        _tick(tid, rows[k, i])
        for k in range(ticks) for i, tid in enumerate(ids)
    ]


def _final_states(rt, ids):
    out = {}
    for tid in ids:
        ten = rt._engines[rt.worker_of(tid)]._lookup(tid)
        assert ten is not None, f"{tid} lost"
        out[tid] = (np.asarray(ten.state.s).copy(), int(ten.state.t))
    return out


def _assert_same_states(got, ref):
    assert got.keys() == ref.keys()
    for tid in ref:
        assert got[tid][1] == ref[tid][1], tid
        np.testing.assert_array_equal(got[tid][0], ref[tid][0])


# ---------------------------------------------------------------------------
# 1. supervisor state machine (unit)
# ---------------------------------------------------------------------------


def test_supervisor_state_machine_transitions():
    sup = WorkerSupervisor(2)
    assert sup.all_healthy()
    assert sup.state(0) == WORKER_HEALTHY

    # a merely-slow worker: suspect, then the late reply clears it
    sup.mark_suspect(0)
    assert sup.state(0) == "suspect" and not sup.all_healthy()
    sup.mark_healthy_probe(0)
    assert sup.state(0) == WORKER_HEALTHY and sup.deaths[0] == 0

    # a real death: detect latency stamped from the first suspicion
    sup.mark_suspect(0)
    time.sleep(0.01)
    detect = sup.mark_dead(0, reason="stall")
    assert sup.state(0) == WORKER_DEAD
    assert detect > 0.0 and sup.detect_s[0] == detect
    assert sup.deaths[0] == 1

    # respawn → recover → first ack stamps the RTO and closes the loop
    sup.mark_respawning(0)
    assert sup.state(0) == "respawning" and sup.respawns[0] == 1
    sup.mark_recovering(0)
    assert sup.state(0) == WORKER_RECOVERING
    assert sup.rto_s[0] is None
    sup.mark_first_ack(0)
    assert sup.state(0) == WORKER_HEALTHY
    assert sup.rto_s[0] is not None and sup.rto_s[0] >= detect

    # worker 1 never left healthy; first_ack on healthy is a no-op
    sup.mark_first_ack(1)
    assert sup.state(1) == WORKER_HEALTHY and sup.all_healthy()

    # instant-EOF death (no suspect phase): detect latency is 0
    assert sup.mark_dead(1, reason="crash") == 0.0


# ---------------------------------------------------------------------------
# 2. kill_worker at every RPC site: typed + bit-identical failover
# ---------------------------------------------------------------------------


def test_kill_worker_every_site_bit_identical_vs_control(tmp_path):
    """The kill matrix: for every client-RPC site of the tick stream,
    SIGKILL-equivalent the targeted worker there.  Every affected
    request surfaces typed `worker_unavailable`; client retry-until-ok
    lands the exact same final state as the never-killed control
    (`kill_worker` fires BEFORE dispatch, so a shed tick was never
    applied and the retry is not a duplicate)."""
    ids = ("c0", "c2")  # one tenant per worker: kills hit both shards
    reqs = _stream(ids, ticks=2)

    ctl = _router(str(tmp_path / "ctl"))
    _register(ctl, ids)
    n_sites = len(reqs)
    for r in reqs:
        assert ctl.handle(r).ok
    ref = _final_states(ctl, ids)
    ctl.close()

    for s in range(1, n_sites + 1):
        rt = _router(str(tmp_path / f"k{s}"))
        _register(rt, ids)
        site = rt._rpc_no + s  # the RPC axis counts from creation
        shed = 0
        with faults.inject(f"kill_worker@{site}"):
            for r in reqs:
                resp = rt.handle(r)
                while not resp.ok:
                    assert resp.error.category == SYSTEM_FAULT
                    assert resp.error.code == "worker_unavailable"
                    shed += 1
                    resp = rt.handle(r)
        assert shed >= 1, f"site {site}: kill never fired"
        assert sum(rt.supervisor.deaths) == 1
        assert rt.supervisor.rto_s[
            rt.supervisor.deaths.index(1)
        ] is not None
        _assert_same_states(_final_states(rt, ids), ref)
        assert rt.worker_states() == [WORKER_HEALTHY] * 2
        rt.close()


def test_double_kill_same_worker_still_bit_identical(tmp_path):
    ids = ("c2", "c3")  # both on worker 1
    reqs = _stream(ids, ticks=3, seed=13)

    ctl = _router(str(tmp_path / "ctl"))
    _register(ctl, ids)
    for r in reqs:
        assert ctl.handle(r).ok
    ref = _final_states(ctl, ids)
    ctl.close()

    rt = _router(str(tmp_path / "rt"))
    _register(rt, ids)
    kills = 0
    for r in reqs:
        # kill the worker at the NEXT rpc, twice over the stream
        if kills < 2:
            kills += 1
            with faults.inject(f"kill_worker@{rt._rpc_no + 1}"):
                resp = rt.handle(r)
            assert not resp.ok
            assert resp.error.code == "worker_unavailable"
            resp = rt.handle(r)
        else:
            resp = rt.handle(r)
        assert resp.ok
    assert rt.supervisor.deaths[1] == 2 and rt.supervisor.respawns[1] == 2
    _assert_same_states(_final_states(rt, ids), ref)
    rt.close()


def test_stall_worker_inproc_degenerates_to_kill(tmp_path):
    rt = _router(str(tmp_path / "rt"))
    _register(rt, ("c0",))
    row = np.zeros(N)
    with faults.inject(f"stall_worker@{rt._rpc_no + 1}"):
        resp = rt.handle(_tick("c0", row))
    assert not resp.ok and resp.error.code == "worker_unavailable"
    assert rt.handle(_tick("c0", row)).ok
    snap = telemetry.snapshot()["counters"]
    assert snap.get('serving.worker.deaths{reason="stall"}', 0) >= 1
    rt.close()


# ---------------------------------------------------------------------------
# 3. in-worker stage kills: admission + every store I/O site
# ---------------------------------------------------------------------------


def test_engine_crash_inside_worker_is_typed_and_recovers(tmp_path):
    """`engine_crash` fires INSIDE the worker at request admission —
    the in-memory engine dies mid-call, the router converts it to a
    typed response, and the respawn serves from the untouched
    partition."""
    rt = _router(str(tmp_path / "rt"))
    _register(rt)
    rng = np.random.default_rng(3)
    w = rt.worker_of("c2")
    assert w == 1
    site = rt._engines[1]._requests + 1
    with faults.inject(f"engine_crash@{site}"):
        resp = rt.handle(_tick("c2", rng.standard_normal(N)))
    assert not resp.ok and resp.error.code == "worker_unavailable"
    assert rt.supervisor.deaths[1] == 1
    # admission kill: the tick was never journaled — the retry is safe
    r2 = rt.handle(_tick("c2", rng.standard_normal(N)))
    assert r2.ok and int(r2.result.t) == T + 1
    # the bystander worker never noticed
    assert rt.supervisor.deaths[0] == 0
    assert rt.handle(_tick("c0", rng.standard_normal(N))).ok
    rt.close()


def test_crash_io_killed_at_every_worker_io_site(tmp_path):
    """Walk the kill point through EVERY tenant-store I/O site of one
    worker's tick window (admit/journal/dispatch/commit from the
    router's seat): each kill surfaces typed, the respawned worker
    recovers its partition under the journal bound
    acked <= recovered <= acked + deaths, and survivors on the other
    worker never miss a tick."""
    rng = np.random.default_rng(23)
    drill = [
        _tick(tid, rng.standard_normal(N))
        for tid in ("c2", "c3", "c2", "c3")
    ]

    site = 0
    killed_sites = 0
    while True:
        site += 1
        rt = _router(str(tmp_path / f"s{site}"))
        _register(rt)
        ops0 = rt._engines[1].store._io_ops
        acked = 0
        # the drill window streams ONLY worker-1 tenants: the crash_io
        # site axis counts each store's own I/O ops, so keeping worker
        # 0 idle inside the window pins which worker the kill hits
        with faults.inject(f"crash_io@{ops0 + site}"):
            for r in drill:
                resp = rt.handle(r)
                assert resp.ok or (
                    resp.error.code == "worker_unavailable"
                ), resp
                acked += bool(resp.ok)
        deaths = rt.supervisor.deaths[1]
        if deaths == 0:
            rt.close()
            break  # site walked past the window's last I/O op: done
        killed_sites += 1
        recovered = sum(
            int(rt._engines[1]._lookup(tid).state.t) - T
            for tid in _W1
        )
        assert acked <= recovered <= acked + deaths, (
            f"site {site}: acked {acked}, recovered {recovered}, "
            f"deaths {deaths}"
        )
        # the survivor shard never noticed and never missed a tick
        assert rt.supervisor.deaths[0] == 0
        assert rt.handle(_tick("c0", rng.standard_normal(N))).ok
        # post-failover the killed worker serves normally again
        assert rt.handle(_tick("c2", rng.standard_normal(N))).ok
        rt.close()
    assert killed_sites >= 4  # the walk covered multiple distinct sites


# ---------------------------------------------------------------------------
# 4. submit/flush failover + refit gang abort + fan-out degradation
# ---------------------------------------------------------------------------


def test_submitted_requests_become_typed_orphans_not_drops(tmp_path):
    rt = _router(str(tmp_path / "rt"))
    _register(rt)
    rng = np.random.default_rng(5)
    reqs = [_tick(tid, rng.standard_normal(N)) for tid in _W0 + _W1]
    rt.submit(reqs)
    rt._inject_kill(1)  # dies holding two submitted-but-unflushed ticks
    out = rt.flush_all()
    # one Response per submission — degraded, never dropped
    assert len(out) == len(reqs)
    by_tenant = {r.tenant: r for r in out}
    for tid in _W0:
        assert by_tenant[tid].ok
    for tid in _W1:
        assert not by_tenant[tid].ok
        assert by_tenant[tid].error.code == "worker_unavailable"
        assert by_tenant[tid].kind == "tick"
    # the dead worker was respawned during the flush fan-out or will be
    # on the next call; a fresh submit round fully succeeds
    rt.submit([_tick(tid, rng.standard_normal(N)) for tid in _W0 + _W1])
    out2 = rt.flush_all()
    assert len(out2) == 4 and all(r.ok for r in out2)
    counters = telemetry.snapshot()["counters"]
    assert counters.get("serving.worker.unavailable_responses", 0) >= 2
    rt.close()


def test_submit_to_dead_worker_orphans_immediately(tmp_path):
    rt = _router(str(tmp_path / "rt"), auto_respawn=False)
    _register(rt)
    rt._inject_kill(1)
    rng = np.random.default_rng(6)
    # the death is discovered ON this submit; the bucket orphans typed
    rt.submit([_tick(tid, rng.standard_normal(N)) for tid in _W0 + _W1])
    out = rt.flush_all()
    assert len(out) == 4
    dead = [r for r in out if not r.ok]
    assert len(dead) == 2
    assert all(r.error.code == "worker_unavailable" for r in dead)
    # without auto-respawn the worker STAYS dead and sheds typed
    assert rt.worker_states()[1] == WORKER_DEAD
    resp = rt.handle(_tick("c2", np.zeros(N)))
    assert not resp.ok and resp.error.code == "worker_unavailable"
    rt.close()


def test_gang_refit_aborts_dead_member_without_wedging(tmp_path):
    rt = _router(str(tmp_path / "rt"))
    _register(rt)
    for tid in ("c0", "c2"):
        assert rt.handle({"kind": "refit", "tenant": tid}).ok
    rt._inject_kill(1)  # dies with its refit queue in memory
    summary = rt.flush_refits()
    # the dead member aborted; the surviving member's refit landed
    assert summary["aborted_workers"] == [1]
    assert summary["n_requests"] == 1 and summary["installed"] == 1
    assert summary["failed"] == []
    # the respawned worker serves again, and a follow-up gang round
    # with the survivor still lands — the barrier never wedged.  (The
    # dead member's queued refit died with its in-memory history:
    # recovered tenants without hist skip silently by design.)
    assert rt.handle(_tick("c2", np.zeros(N))).ok
    assert rt.handle({"kind": "refit", "tenant": "c0"}).ok
    summary2 = rt.flush_refits()
    assert summary2["aborted_workers"] == []
    assert summary2["installed"] == 1 and summary2["failed"] == []
    rt.close()


def test_check_liveness_detects_between_requests(tmp_path):
    rt = _router(str(tmp_path / "rt"))
    _register(rt, ("c0",))
    assert rt.check_liveness() == [WORKER_HEALTHY] * 2
    rt._inject_kill(1)
    # the sweep itself discovers the corpse and triggers the respawn
    states = rt.check_liveness()
    assert states[0] == WORKER_HEALTHY
    assert states[1] in (WORKER_RECOVERING, WORKER_HEALTHY)
    assert rt.supervisor.deaths[1] == 1
    # next sweep's ping acks the recovered worker back to healthy
    assert rt.check_liveness() == [WORKER_HEALTHY] * 2
    rt.close()


# ---------------------------------------------------------------------------
# 5. satellites: _sanitize, recover hygiene, close hardening
# ---------------------------------------------------------------------------


def test_sanitize_scrubs_nonfinite_scalars_and_counts():
    before = telemetry.snapshot()["counters"].get(
        "serving.sanitize.nonfinite", 0
    )
    out = _sanitize({
        "a": float("nan"),
        "b": [1.5, float("inf"), float("-inf")],
        "arr": np.array([np.nan, 1.0]),
        "s": "x", "n": 3,
    })
    assert out["a"] is None
    assert out["b"] == [1.5, None, None]
    # arrays are bulk state: passed through UNMAPPED, NaN and all
    np.testing.assert_array_equal(
        out["arr"], np.array([np.nan, 1.0])
    )
    assert out["s"] == "x" and out["n"] == 3
    after = telemetry.snapshot()["counters"].get(
        "serving.sanitize.nonfinite", 0
    )
    assert after - before == 3


def test_router_recover_quarantines_planted_partition_junk(tmp_path):
    store = str(tmp_path / "rt")
    rt = _router(store)
    _register(rt)
    rng = np.random.default_rng(9)
    for tid in _W0 + _W1:
        assert rt.handle(_tick(tid, rng.standard_normal(N))).ok
    rt.close()

    # plant quarantine artifacts + in-flight temps in ONE partition
    part0 = worker_partition(store, 0)
    strays = (
        "ghost.npz.corrupt", "c0.npz.tmp.1234", "weird.corrupt",
        "c0.journal.tmp.7", "zz.journal.corrupt",
    )
    for stray in strays:
        with open(os.path.join(part0, stray), "wb") as f:
            f.write(b"\x00junk")

    rt2 = _router(store)
    rec = rt2.recover(prewarm=8)
    # seed lives on BOTH partitions (register_seed); clones on their own
    assert sum(r["tenants_on_disk"] for r in rec) == 6
    # the junk neither crashed recovery nor resurrected as tenants
    # (seed is legitimately on BOTH partitions, so it lists twice)
    assert sorted(rt2.tenant_ids()) == sorted(
        _W0 + _W1 + ("seed", "seed")
    )
    r = rt2.handle(_tick("c0", np.zeros(N)))
    assert r.ok and int(r.result.t) == T + 2
    # strays are still quarantined on disk, invisible, untouched
    for stray in strays:
        assert os.path.exists(os.path.join(part0, stray))
    assert len(glob.glob(os.path.join(part0, "*.corrupt"))) == 3
    rt2.close()


def test_close_idempotent_and_exit_never_raises(tmp_path):
    rt = _router(str(tmp_path / "rt"))
    _register(rt, ("c0",))
    rt.close()
    rt.close()  # second close is a no-op, not an error
    assert rt._closed

    # __exit__ swallows even a close() that raises
    with _router(None) as rt2:
        pass
    rt3 = _router(None)
    rt3.close = lambda: (_ for _ in ()).throw(RuntimeError("boom"))
    assert rt3.__exit__(None, None, None) is False
    TenantRouter.close(rt3)  # real cleanup


# ---------------------------------------------------------------------------
# 6. observability: worker_id records, glyph column, flight bundles
# ---------------------------------------------------------------------------


def test_router_records_carry_worker_id_standalone_engine_does_not(
    tmp_path,
):
    sink = str(tmp_path / "t.jsonl")
    telemetry.enable(sink=sink)
    rt = _router(str(tmp_path / "rt"))
    _register(rt, ("c0", "c2"))
    rng = np.random.default_rng(4)
    assert rt.handle(_tick("c0", rng.standard_normal(N))).ok
    assert rt.handle(_tick("c2", rng.standard_normal(N))).ok
    rt.close()
    eng = ServingEngine(max_em_iter=3, retry_policy=_POLICY)
    eng.register("solo", _panel(5))
    assert eng.handle(_tick("solo", rng.standard_normal(N))).ok

    lines = [json.loads(ln) for ln in open(sink)]
    served = [
        ln for ln in lines
        if ln.get("entry") == "serving" and ln.get("kind") == "tick"
    ]
    routed = [ln for ln in served if "worker_id" in ln]
    # routed ticks are attributed to their owning worker...
    assert sorted(ln["worker_id"] for ln in routed) == [0, 1]
    # ...and a standalone engine's records are byte-compatible with
    # pre-supervision vintage: no worker_id key at all
    solo = [ln for ln in served if ln not in routed]
    assert solo and all("worker_id" not in ln for ln in solo)


def test_summarize_worker_glyph_column(tmp_path):
    sink = str(tmp_path / "t.jsonl")
    serving_line = {
        "run_id": "s1", "entry": "serving", "time_unix": 3.0,
        "wall_s": 0.01, "kind": "tick", "outcome": "ok",
        "worker_id": 1,
    }
    metrics_line = {
        "entry": "metrics", "time_unix": 4.0, "counters": {},
        "gauges": {
            'serving.worker.state{worker="0"}': 0.0,
            'serving.worker.state{worker="1"}': 2.0,
            'serving.worker.state{worker="2"}': 4.0,
        },
    }
    old_line = {
        "run_id": "e1", "entry": "estimate_dfm_em", "time_unix": 1.0,
        "wall_s": 1.0,
    }
    with open(sink, "w") as f:
        for ln in (old_line, serving_line, metrics_line):
            f.write(json.dumps(ln) + "\n")
    out = telemetry.summarize(sink)
    assert "workers" in out
    # healthy / dead / recovering render as lifecycle glyphs
    assert "w0✓ w1✗ w2↻" in out
    # the non-serving aggregate row degrades to "-", nothing crashes
    erow = [
        ln for ln in out.splitlines() if ln.startswith("estimate_dfm_em")
    ]
    assert erow and "w0" not in erow[0]


def test_worker_death_dumps_forced_flight_bundle(tmp_path):
    sink = str(tmp_path / "obs" / "t.jsonl")
    telemetry.enable(sink=sink)
    rt = _router(str(tmp_path / "rt"))
    _register(rt, ("c2",))
    flight.reset()  # registration noise out; drill from a clean ring
    rng = np.random.default_rng(8)
    # two deaths back to back: FORCED dumps ignore the throttle window
    for _ in range(2):
        with faults.inject(f"kill_worker@{rt._rpc_no + 1}"):
            resp = rt.handle(_tick("c2", rng.standard_normal(N)))
        assert not resp.ok
        assert rt.handle(_tick("c2", rng.standard_normal(N))).ok
    bundles = glob.glob(
        os.path.join(str(tmp_path / "obs"), "flight",
                     "flight-*worker_dead*.json")
    )
    assert len(bundles) >= 1
    with open(sorted(bundles)[-1]) as f:
        bundle = json.load(f)
    assert bundle["trigger"]["trigger"] == "worker_dead"
    assert bundle["trigger"]["worker"] == 1
    deaths = [
        ev for ev in bundle["ring"] if ev["kind"] == "worker_dead"
    ]
    assert deaths and deaths[-1]["reason"] == "kill"
    assert deaths[-1]["severity"] == "error"
    rt.close()


def test_flush_metrics_exports_supervisor_gauges(tmp_path):
    sink = str(tmp_path / "t.jsonl")
    telemetry.enable(sink=sink)
    rt = _router(str(tmp_path / "rt"))
    _register(rt, ("c0", "c2"))
    rt._inject_kill(1)
    resp = rt.handle(_tick("c2", np.zeros(N)))  # discover + respawn
    assert not resp.ok
    assert rt.handle(_tick("c2", np.zeros(N))).ok
    rt.flush_metrics()
    rt.close()
    lines = [json.loads(ln) for ln in open(sink)]
    gauges = {}
    for ln in lines:
        if ln.get("entry") == "metrics":
            gauges.update(ln.get("gauges") or {})
    assert gauges.get('serving.worker.state{worker="0"}') == 0.0
    assert gauges.get('serving.worker.state{worker="1"}') == 0.0
    assert 'serving.worker.rto_s{worker="1"}' in gauges
    assert 'serving.worker.detect_s{worker="1"}' in gauges


# ---------------------------------------------------------------------------
# 7. process backend: SIGKILL, real stall, wedged close (slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_process_backend_kill_stall_and_rto(tmp_path):
    """OS-process drill: a SIGKILLed worker is detected on pipe EOF
    (typed response, respawn, recover, RTO stamped); a stalled worker
    is declared dead within the heartbeat deadline
    rpc_timeout_s + suspect_grace_s — the router never hangs."""
    store = str(tmp_path / "rt")
    rt = TenantRouter(2, store_dir=store, backend="process")
    try:
        rt.register_seed("seed", _panel(2))
        for tid in ("c0", "c2"):
            rt.register_shared(tid, "seed")
        rng = np.random.default_rng(2)
        # warm both shards under the generous boot deadline, THEN
        # tighten the liveness knobs for the drill
        assert rt.handle(_tick("c0", rng.standard_normal(N))).ok
        assert rt.handle(_tick("c2", rng.standard_normal(N))).ok
        rt.rpc_timeout_s = 4.0
        rt.suspect_grace_s = 1.0

        # --- SIGKILL drill ---
        w = rt.worker_of("c2")
        with faults.inject(f"kill_worker@{rt._rpc_no + 1}"):
            resp = rt.handle(_tick("c2", rng.standard_normal(N)))
        assert not resp.ok and resp.error.code == "worker_unavailable"
        assert rt.supervisor.deaths[w] == 1
        r2 = rt.handle(_tick("c2", rng.standard_normal(N)))
        assert r2.ok
        assert rt.supervisor.rto_s[w] is not None
        # survivor shard never noticed
        assert rt.handle(_tick("c0", rng.standard_normal(N))).ok

        # --- stall drill: detect latency bounded by the deadline ---
        with faults.inject(f"stall_worker@{rt._rpc_no + 1}"):
            t0 = time.perf_counter()
            resp = rt.handle(_tick("c2", rng.standard_normal(N)))
            wall = time.perf_counter() - t0
        assert not resp.ok and resp.error.code == "worker_unavailable"
        deadline = rt.rpc_timeout_s + rt.suspect_grace_s
        assert rt.supervisor.detect_s[w] <= deadline + 0.5
        # the wall includes detect + reap + respawn boot; the DETECT
        # portion is what the heartbeat deadline bounds
        assert wall >= rt.rpc_timeout_s
        snap = telemetry.snapshot()["counters"]
        assert snap.get('serving.worker.deaths{reason="stall"}', 0) >= 1
        assert rt.handle(_tick("c2", rng.standard_normal(N))).ok
    finally:
        rt.close()
    # close reaped everything: no orphan worker processes
    assert all(p is None or not p.is_alive() for p in rt._procs)


@pytest.mark.slow
def test_process_close_escalates_on_wedged_worker(tmp_path):
    """A worker wedged in a stall must not hang `close()`: the polite
    phase is bounded by close_timeout_s, then terminate → SIGKILL."""
    rt = TenantRouter(2, backend="process", close_timeout_s=2.0)
    try:
        rt._inject_stall(0)  # worker 0 sleeps far past any close budget
        time.sleep(0.2)
    finally:
        t0 = time.perf_counter()
        rt.close()
        wall = time.perf_counter() - t0
    assert wall < 30.0  # bounded: 2s polite phase + escalation joins
    assert all(p is None or not p.is_alive() for p in rt._procs)
    rt.close()  # and still idempotent afterwards
    assert rt.__exit__(None, None, None) is False
