"""External validation of configs 4-5 against their published methodology.

The reference implements neither estimator (they are named targets:
/root/reference/README.md lines 4-6); their specification is the papers.  The
published EMPIRICAL tables (e.g. Forni-Gambetti 2010 JME Tables 1-2) are
computed on proprietary panels that are not vendored with the reference and
cannot be fetched here, so this module validates against the two strongest
offline-checkable forms of the published results instead (docs/VALIDATION.md
records the full rationale):

1. ANALYTIC population identities of the published estimators, with exact
   closed-form target values (Forni-Hallin-Lippi-Reichlin 2000, Rev. Econ.
   Stat. 82(4), sec. 2: the dynamic eigenvalues of a q=1 GDFM are
   lambda_1(theta) = ||b||^2 s_f(theta) + sigma^2/2pi and the remaining N-1
   equal the idiosyncratic spectrum sigma^2/2pi).
2. An INDEPENDENT direct-DFT oracle implementation of the FHLR spectral
   estimator (straight from the lag-window formula, no FFT) that the
   production FFT path must match to near machine precision.
3. The Breitung-Eickmeier (2016, J. Banking & Finance 72) / Barigozzi-style
   two-level Monte Carlo design: AR(1) global + block factors, N(0,1)
   loadings, unit idiosyncratic noise — asserting the paper's qualitative
   consistency result quantitatively (recovery rates at the design's sizes,
   improvement in N_b) plus an exact reduction identity to the one-level
   model.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.dynpca import dynamic_pca, spectral_density
from dynamic_factor_models_tpu.models.multilevel import estimate_multilevel_dfm
from dynamic_factor_models_tpu.ops.cca import canonical_correlations
from dynamic_factor_models_tpu.ops.linalg import standardize_data


# ---------------------------------------------------------------------------
# config 4: FHLR / Forni-Gambetti dynamic PCA
# ---------------------------------------------------------------------------


class TestFHLRAnalyticSpectrum:
    """Population dynamic-eigenvalue identity of FHLR (2000), sec. 2.

    DGP: x_it = b_i f_t + sigma e_it with f_t AR(1), var(f) = 1, |b_i| = 1.
    After per-series standardization (scale c^2 = 1 + sigma^2):

        lambda_1(theta) = N bt^2 s_f(theta) + st^2 / 2pi
        lambda_j(theta) = st^2 / 2pi            (j = 2..N)
        s_f(theta)      = (1 - rho^2) / (2pi |1 - rho e^{-i theta}|^2)

    with bt^2 = 1/c^2, st^2 = sigma^2/c^2.  Tolerances reflect the known
    Bartlett lag-window bias/variance at T=6000, M=48 (calibrated: median
    rel. err. 0.051, p90 0.129, max 0.185; noise floor 1.2%; share 0.002).
    """

    T, N, RHO, SIG, M = 6000, 20, 0.5, 0.5, 48

    @pytest.fixture(scope="class")
    def fitted(self):
        rng = np.random.default_rng(0)
        u = rng.standard_normal(self.T) * np.sqrt(1 - self.RHO**2)
        f = np.zeros(self.T)
        for t in range(1, self.T):
            f[t] = self.RHO * f[t - 1] + u[t]
        b = rng.choice([-1.0, 1.0], self.N)
        x = np.outer(f, b) + self.SIG * rng.standard_normal((self.T, self.N))
        return dynamic_pca(x, q=1, M=self.M)

    def _analytic(self):
        H = 2 * self.M + 1
        th = 2.0 * np.pi * np.arange(H) / H
        c2 = 1.0 + self.SIG**2
        bt2, st2 = 1.0 / c2, self.SIG**2 / c2
        sf = (1 - self.RHO**2) / (
            2 * np.pi * np.abs(1 - self.RHO * np.exp(-1j * th)) ** 2
        )
        return self.N * bt2 * sf + st2 / (2 * np.pi), st2 / (2 * np.pi)

    def test_top_dynamic_eigenvalue_matches_analytic(self, fitted):
        lam1, _ = self._analytic()
        rel = np.abs(np.asarray(fitted.eigenvalues)[:, 0] / lam1 - 1.0)
        assert np.median(rel) < 0.10, f"median rel err {np.median(rel):.3f}"
        assert np.quantile(rel, 0.9) < 0.20
        assert rel.max() < 0.30

    def test_noise_eigenvalues_match_idio_spectrum(self, fitted):
        _, floor = self._analytic()
        noise = float(np.asarray(fitted.eigenvalues)[:, 1:].mean())
        assert abs(noise / floor - 1.0) < 0.05

    def test_variance_share_matches_analytic(self, fitted):
        c2 = 1.0 + self.SIG**2
        share = (self.N * (1.0 / c2) + self.SIG**2 / c2) / self.N  # 0.81
        assert abs(float(fitted.variance_share) - share) < 0.02


def test_spectral_density_matches_direct_dft_oracle():
    """Independent-path oracle: the production FFT lag-window estimator must
    equal a direct evaluation of the published formula

        Sigma(theta_h) = (1/2pi) sum_{k=-M}^{M} w_|k| Gamma_k e^{-i k theta_h}

    written as explicit NumPy sums (FHLR 2000 eq. (4)-(5) with a Bartlett
    window; Gamma_{-k} = Gamma_k', pairwise-complete normalization).  Catches
    FFT-ordering, windowing, and hermitization translation errors.
    """
    rng = np.random.default_rng(5)
    T, N, M = 300, 8, 16
    x = np.cumsum(rng.standard_normal((T, N)), axis=0) * 0.1
    x += rng.standard_normal((T, N))

    freqs, spec = spectral_density(x, M=M)

    xz = np.asarray(standardize_data(jnp.asarray(x))[0])
    H = 2 * M + 1
    gam = np.stack(
        [(xz[k:].T @ xz[: T - k]) / (T - k) for k in range(M + 1)]
    )  # (M+1, N, N), Gamma_k = E[x_t x_{t-k}']
    w = 1.0 - np.arange(M + 1) / (M + 1)
    oracle = np.zeros((H, N, N), complex)
    for h in range(H):
        th = 2.0 * np.pi * h / H
        acc = w[0] * gam[0].astype(complex)
        for k in range(1, M + 1):
            acc += w[k] * (
                gam[k] * np.exp(-1j * k * th) + gam[k].T * np.exp(1j * k * th)
            )
        oracle[h] = acc / (2.0 * np.pi)
    np.testing.assert_allclose(np.asarray(spec), oracle, atol=1e-10)
    np.testing.assert_allclose(
        np.asarray(freqs), 2.0 * np.pi * np.arange(H) / H, atol=1e-12
    )


# ---------------------------------------------------------------------------
# Hallin-Liska (2007) dynamic factor-count criterion
# ---------------------------------------------------------------------------


class TestHallinLiska:
    """Hallin-Liska (2007, JASA 102(478)) self-calibrating IC: recovers the
    true q on GDFM designs across (N, T) subsamples — the paper's Monte
    Carlo claim, asserted quantitatively on the analytic FHLR q=1 design
    plus dynamic-loading q=2/q=3 panels."""

    @staticmethod
    def _gdfm(T, N, q, rho=0.7, sig=0.6, seed=0):
        rng = np.random.default_rng(seed)
        f = np.zeros((T, q))
        for t in range(1, T):
            f[t] = rho * f[t - 1] + rng.standard_normal(q) * np.sqrt(
                1.0 - rho**2
            )
        b0 = rng.standard_normal((N, q))
        b1 = 0.5 * rng.standard_normal((N, q))  # one-lag dynamic loadings
        flag = np.vstack([np.zeros((1, q)), f[:-1]])
        return f @ b0.T + flag @ b1.T + sig * rng.standard_normal((T, N))

    @pytest.mark.parametrize(
        "q_true,T,N", [(1, 400, 30), (2, 400, 40), (3, 350, 45)]
    )
    def test_recovers_q(self, q_true, T, N):
        from dynamic_factor_models_tpu.models.dynpca import hallin_liska_q

        res = hallin_liska_q(self._gdfm(T, N, q_true), q_max=8)
        assert res.q == q_true
        # the selection is a genuine stability interval: zero variance
        # across the nested subsamples wherever the full-sample pick is q
        sel = res.q_by_c == q_true
        assert (res.stability[sel] == 0).any()

    @pytest.mark.slow
    def test_recovers_q3_larger_panel(self):
        from dynamic_factor_models_tpu.models.dynpca import hallin_liska_q

        res = hallin_liska_q(self._gdfm(500, 50, 3), q_max=8)
        assert res.q == 3

    def test_subsample_ladder_ends_at_full_panel(self):
        from dynamic_factor_models_tpu.models.dynpca import hallin_liska_q

        x = self._gdfm(200, 20, 1)
        res = hallin_liska_q(x, q_max=5, n_subsamples=3)
        assert res.sub_sizes[-1] == (20, 200)
        assert res.q_subsamples.shape == (3, res.c_grid.size)

    def test_validation_errors(self):
        from dynamic_factor_models_tpu.models.dynpca import hallin_liska_q

        x = self._gdfm(120, 12, 1)
        with pytest.raises(ValueError, match="criterion"):
            hallin_liska_q(x, criterion="nope")
        with pytest.raises(ValueError, match="q_max"):
            hallin_liska_q(x, q_max=12)
        with pytest.raises(ValueError, match="subsamples"):
            hallin_liska_q(x, q_max=3, n_subsamples=1)

    def test_missing_data_tolerated(self):
        from dynamic_factor_models_tpu.models.dynpca import hallin_liska_q

        x = self._gdfm(300, 25, 1, seed=3)
        x[np.random.default_rng(0).random(x.shape) < 0.05] = np.nan
        res = hallin_liska_q(x, q_max=6)
        assert res.q == 1

    @pytest.mark.slow
    @pytest.mark.parametrize("which", ["Real", "All"])
    def test_real_panel_selects_one_dynamic_factor(self, which, request):
        """Regression pin on the Stock-Watson panels: HL selects q = 1 on
        both the :Real and :All included panels — consistent with the
        chapter's one-dominant-dynamic-factor reading (the Table 2(C)
        Amengual-Watson ICp minimum sits at small dynamic counts)."""
        from dynamic_factor_models_tpu.models.dynpca import hallin_liska_q

        ds = request.getfixturevalue(f"dataset_{which.lower()}")
        x = np.asarray(ds.bpdata)[:, np.asarray(ds.inclcode) == 1][2:224]
        res = hallin_liska_q(x, q_max=8)
        assert res.q == 1


# ---------------------------------------------------------------------------
# config 5: Breitung-Eickmeier / Barigozzi two-level DFM
# ---------------------------------------------------------------------------


def _be_panel(nb: int, seed: int, T: int = 200, B: int = 4, rho: float = 0.7):
    """The canonical two-level Monte Carlo design (Breitung-Eickmeier 2016,
    sec. 4): one AR(1) global factor, one AR(1) factor per block, standard-
    normal loadings, unit idiosyncratic noise."""
    rng = np.random.default_rng(seed)

    def ar1():
        u = rng.standard_normal((T, 1)) * np.sqrt(1 - rho**2)
        f = np.zeros((T, 1))
        for t in range(1, T):
            f[t] = rho * f[t - 1] + u[t]
        return f

    F = ar1()
    G = [ar1() for _ in range(B)]
    x = np.zeros((T, B * nb))
    gcomp = np.zeros_like(x)
    bcomp = np.zeros_like(x)
    for c in range(B):
        Lg = rng.standard_normal((nb, 1))
        Lb = rng.standard_normal((nb, 1))
        s = slice(c * nb, (c + 1) * nb)
        gcomp[:, s] = F @ Lg.T
        bcomp[:, s] = G[c] @ Lb.T
        x[:, s] = gcomp[:, s] + bcomp[:, s] + rng.standard_normal((T, nb))
    blocks = [np.arange(c * nb, (c + 1) * nb) for c in range(B)]
    return x, F, G, blocks, gcomp, bcomp


class TestBreitungEickmeierDesign:
    def _recovery(self, nb, seed):
        x, F, G, blocks, _, _ = _be_panel(nb, seed)
        res = estimate_multilevel_dfm(x, blocks, 1, 1)
        cc = float(
            np.asarray(canonical_correlations(res.global_factors, jnp.asarray(F)))[0]
        )
        bcc = np.mean(
            [
                abs(
                    np.corrcoef(
                        np.asarray(res.block_factors[c][:, 0]), G[c][:, 0]
                    )[0, 1]
                )
                for c in range(len(G))
            ]
        )
        return cc, bcc

    def test_recovery_rates_at_design_size(self):
        """At the paper's N_b=30, T=200: global CCA > 0.98, mean block-factor
        correlation > 0.93 (calibrated: >= 0.9919 / >= 0.9592 over 3 seeds)."""
        for seed in (0, 1, 2):
            cc, bcc = self._recovery(30, seed)
            assert cc > 0.98, f"seed {seed}: global CCA {cc:.4f}"
            assert bcc > 0.93, f"seed {seed}: block corr {bcc:.4f}"

    def test_consistency_in_block_size(self):
        """The paper's consistency result: recovery improves as N_b grows
        (10 -> 30), for the global and block spaces alike."""
        small = np.array([self._recovery(10, s) for s in (0, 1, 2)])
        large = np.array([self._recovery(30, s) for s in (0, 1, 2)])
        assert large[:, 0].mean() > small[:, 0].mean() - 0.01
        assert large[:, 1].mean() > small[:, 1].mean() - 0.01
        # and the design sizes sit in the published recovery range
        assert small[:, 0].min() > 0.9

    def test_variance_decomposition_matches_realized_shares(self):
        """The estimated global/block variance decomposition reproduces the
        REALIZED shares of the simulated components (computable exactly from
        the DGP's common terms), within Monte-Carlo tolerance."""
        x, _, _, blocks, gcomp, bcomp = _be_panel(30, 0)
        res = estimate_multilevel_dfm(x, blocks, 1, 1)
        std = x.std(axis=0)
        tot = ((x / std) ** 2).sum()
        realized_g = ((gcomp / std) ** 2).sum() / tot
        realized_b = ((bcomp / std) ** 2).sum() / tot
        vd = res.variance_decomposition
        assert abs(vd["global"] - realized_g) < 0.05
        assert abs(vd["block"] - realized_b) < 0.05
        assert abs(sum(vd.values()) - 1.0) < 0.05

    def test_reduces_to_one_level_without_block_structure(self):
        """Exact reduction: with zero block loadings the two-level global
        estimate must span the one-level ALS factor space."""
        from dynamic_factor_models_tpu.models.dfm import DFMConfig, estimate_factor

        rng = np.random.default_rng(7)
        T, N = 200, 60
        f = np.zeros((T, 2))
        for t in range(1, T):
            f[t] = 0.6 * f[t - 1] + rng.standard_normal(2) * 0.8
        x = f @ rng.standard_normal((N, 2)).T + rng.standard_normal((T, N))
        blocks = [np.arange(0, 30), np.arange(30, 60)]
        ml = estimate_multilevel_dfm(x, blocks, 2, 1, max_outer=1)
        f1, _ = estimate_factor(
            jnp.asarray(x),
            np.ones(N, np.int64),
            0,
            T - 1,
            DFMConfig(nfac_u=2, tol=1e-10),
        )
        cc = np.asarray(canonical_correlations(ml.global_factors, f1))
        assert cc.min() > 0.999, f"one-level reduction broken: CCA {cc}"
