"""Chaos suite: numerical-health guardrails under deterministic fault
injection (utils/guards + utils/faults + the guarded emloop).

Selection contract (pytest.ini): everything here carries the `chaos`
marker; the default subset uses toy module-level EM steps on tiny
pytrees so it rides in the tier-1 fast lane, and the full-scale drills
(real estimation entry points) are additionally marked `slow`.

The toy step family below keeps the guarded while-loop's compile
surface minimal: a two-parameter contraction with an analytically
monotone "log-likelihood" (negative squared distance to the target),
plus a diverging twin whose loglik genuinely decreases every iteration
— the only way to exercise the DECREASE sentinel and ladder exhaustion
deterministically without a pathological panel.
"""

import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.emloop import run_em_loop
from dynamic_factor_models_tpu.utils import faults, guards, telemetry

pytestmark = pytest.mark.chaos


class ToyParams(NamedTuple):
    theta: jnp.ndarray  # (2,) the "estimate"
    Q: jnp.ndarray  # (2, 2) innovation covariance (jitter/poison target)


def _toy_params():
    return ToyParams(
        theta=jnp.asarray([1.0, -2.0]), Q=jnp.eye(2)
    )


def toy_step(params, target):
    """Contraction toward `target`: loglik (of the INPUT, per the loop
    contract) is -||theta - target||^2, strictly increasing along the
    trajectory; Q passes through untouched."""
    ll = -jnp.sum((params.theta - target) ** 2)
    return ToyParams(
        target + 0.5 * (params.theta - target), params.Q
    ), ll


def toy_step_diverging(params, target):
    """Anti-contraction: theta moves AWAY from the target, so the
    loglik DECREASES every iteration — a deterministic monotonicity
    violation no jitter rung can repair."""
    ll = -jnp.sum((params.theta - target) ** 2)
    return ToyParams(
        target + 2.0 * (params.theta - target), params.Q
    ), ll


_TARGET = jnp.asarray([0.5, 0.25])


def _counters():
    return telemetry.snapshot()["counters"]


def _delta(a, b):
    return max(
        float(np.max(np.abs(np.asarray(x) - np.asarray(y))))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


# ---------------------------------------------------------------------------
# fault-spec grammar
# ---------------------------------------------------------------------------


def test_fault_spec_grammar():
    plan = faults.parse_spec("nan_estep@3;chol_fail@7+")
    assert plan.nan_estep == 3 and plan.chol_fail == 7
    assert plan.persistent == frozenset({"chol_fail"})
    assert plan.any()
    # checkpoint kinds default to site 1; separators ';' and ',' both work
    plan = faults.parse_spec("ckpt_corrupt, preempt@2")
    assert plan.ckpt_corrupt == 1 and plan.preempt == 2
    assert faults.parse_spec("") == faults.EMPTY_PLAN
    assert not faults.EMPTY_PLAN.any()
    for bad in (
        "gamma_ray@3",  # unknown kind
        "nan_estep",  # in-loop kinds need an explicit iteration
        "nan_estep@0",  # sites are 1-based
        "nan_estep@x",  # not an int
        "nan_estep@2;nan_estep@3",  # duplicate clause
        "preempt@1+",  # checkpoint kinds cannot be persistent
    ):
        with pytest.raises(ValueError):
            faults.parse_spec(bad)


def test_guard_env_switches(monkeypatch):
    monkeypatch.delenv("DFM_GUARDS", raising=False)
    assert guards.guards_enabled()
    for off in ("0", "false", "off", "no", ""):
        monkeypatch.setenv("DFM_GUARDS", off)
        assert not guards.guards_enabled()
    monkeypatch.setenv("DFM_GUARDS", "1")
    assert guards.guards_enabled()
    monkeypatch.delenv("DFM_GUARD_DROP_TOL", raising=False)
    assert guards.drop_tol() == 1e-3
    monkeypatch.setenv("DFM_GUARD_DROP_TOL", "0.5")
    assert guards.drop_tol() == 0.5
    monkeypatch.setenv("DFM_GUARD_DROP_TOL", "-1")
    with pytest.raises(ValueError):
        guards.drop_tol()
    monkeypatch.setenv("DFM_GUARD_DROP_TOL", "nan")
    with pytest.raises(ValueError):
        guards.drop_tol()


# ---------------------------------------------------------------------------
# in-loop faults: detect, recover, match the clean run
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("spec,kind", [
    ("nan_estep@3", "nan_estep"),
    ("chol_fail@3", "chol_fail"),
])
def test_inloop_fault_recovers_to_clean_result(spec, kind):
    """A transient injected fault must be detected, recovered via the
    first ladder rung, and leave the final params within 1e-8 of the
    uninjected run — the jitter epsilon is a no-op on an
    already-well-conditioned covariance, so the retry replays the clean
    trajectory."""
    params, args = _toy_params(), (_TARGET,)
    clean = run_em_loop(toy_step, params, args, 1e-9, 40, guard=True)
    assert clean.health == guards.HEALTH_OK and clean.faults_detected == 0
    c0 = _counters()
    with faults.inject(spec):
        res = run_em_loop(toy_step, params, args, 1e-9, 40, guard=True)
    c1 = _counters()
    assert res.health == guards.HEALTH_OK
    assert res.faults_detected == 1 and res.recoveries == 1
    assert list(res.rungs_used) == ["jitter"]
    assert res.converged
    assert _delta(res.params, clean.params) < 1e-8
    # telemetry: detection, recovery, and the injection itself all count
    assert c1["em_guard.faults_detected"] == c0.get(
        "em_guard.faults_detected", 0) + 1
    assert c1["em_guard.recoveries"] == c0.get("em_guard.recoveries", 0) + 1
    assert c1["faults_injected." + kind] >= c0.get(
        "faults_injected." + kind, 0) + 1


def test_persistent_decrease_exhausts_ladder_returns_last_good():
    """A genuinely diverging step trips the DECREASE sentinel on every
    attempt: the ladder tries both jitter rungs (demote has no fallback
    here; promote_f64 is skipped — params are already f64 under the test
    config), then returns the LAST-GOOD params with health flagged,
    never raising."""
    params, args = _toy_params(), (_TARGET,)
    res = run_em_loop(toy_step_diverging, params, args, 1e-9, 40, guard=True)
    assert res.health == guards.HEALTH_DECREASE
    assert not res.converged
    assert list(res.rungs_used) == ["jitter", "jitter_grown"]
    assert res.faults_detected == 3  # initial trip + one per jitter rung
    assert res.recoveries == 2  # rung attempts that resumed the loop
    # last-good: theta was never replaced by a diverged iterate (the
    # jitter rungs only touch Q, and Q=I is a fixed point of the repair)
    np.testing.assert_allclose(
        np.asarray(res.params.theta), np.asarray(params.theta), atol=1e-12
    )
    assert bool(guards.tree_finite(res.params))


def test_nan_estep_host_loop_sentinel():
    """collect_path=True runs the host-synced diagnostic loop: the
    sentinel stops on the poisoned iteration and preserves last-good
    params, but does NOT run the ladder (preserved trip state beats an
    automatic retry when a human is watching)."""
    params, args = _toy_params(), (_TARGET,)
    with faults.inject("nan_estep@4"):
        # host loop has no injection machinery — drive the sentinel with
        # the diverging step instead, which the DECREASE branch catches
        res = run_em_loop(
            toy_step_diverging, params, args, 1e-9, 10, guard=True,
            collect_path=True,
        )
    assert res.health == guards.HEALTH_DECREASE
    assert res.faults_detected == 1 and res.recoveries == 0
    assert not res.converged
    np.testing.assert_allclose(
        np.asarray(res.params.theta), np.asarray(params.theta), atol=1e-12
    )


# ---------------------------------------------------------------------------
# satellite: the converged flag reports the tolerance break, not the cap
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("guard", [True, False])
def test_converged_flag_reports_tolerance_break_device(guard):
    params, args = _toy_params(), (_TARGET,)
    full = run_em_loop(toy_step, params, args, 1e-9, 40, guard=guard)
    assert full.converged and full.n_iter < 40
    # a cap below the tolerance break point must NOT report converged,
    # even though n_iter < max_em_iter is impossible here (the old bug
    # reported `it < host_cap` as convergence)
    capped = run_em_loop(toy_step, params, args, 1e-9, 3, guard=guard)
    assert capped.n_iter == 3 and not capped.converged
    # convergence exactly on the final permitted iteration still counts:
    # rerun with the cap set to the actual break iteration
    exact = run_em_loop(toy_step, params, args, 1e-9, full.n_iter, guard=guard)
    assert exact.n_iter == full.n_iter and exact.converged
    # tol=0 never breaks: runs to the cap, not converged
    never = run_em_loop(toy_step, params, args, 0.0, 5, guard=guard)
    assert never.n_iter == 5 and not never.converged


def test_converged_flag_reports_tolerance_break_host():
    params, args = _toy_params(), (_TARGET,)
    full = run_em_loop(
        toy_step, params, args, 1e-9, 40, guard=True, collect_path=True
    )
    assert full.converged and full.n_iter < 40
    capped = run_em_loop(
        toy_step, params, args, 1e-9, 3, guard=True, collect_path=True
    )
    assert capped.n_iter == 3 and not capped.converged
    exact = run_em_loop(
        toy_step, params, args, 1e-9, full.n_iter, guard=True,
        collect_path=True,
    )
    assert exact.converged


# ---------------------------------------------------------------------------
# checkpoint integrity: checksum, quarantine, clean restart, preemption
# ---------------------------------------------------------------------------


def test_checkpoint_checksum_roundtrip_and_quarantine(tmp_path):
    from dynamic_factor_models_tpu.utils.checkpoint import (
        CheckpointCorruptError,
        load_pytree,
        save_pytree,
    )

    tree = {"a": np.arange(6.0).reshape(2, 3), "b": np.ones(4, np.float32)}
    p = str(tmp_path / "ok.npz")
    save_pytree(p, tree)
    back = load_pytree(p, tree)
    np.testing.assert_array_equal(back["a"], tree["a"])

    for mode in ("truncate", "flip"):
        p2 = str(tmp_path / f"bad_{mode}.npz")
        save_pytree(p2, tree)
        faults.corrupt_file(p2, mode=mode)
        with pytest.raises(CheckpointCorruptError):
            load_pytree(p2, tree)
        assert not os.path.exists(p2), "corrupt archive must be moved away"
        assert os.path.exists(p2 + ".corrupt"), "quarantine file missing"

    # structural mismatch is caller error, not corruption: still ValueError
    p3 = str(tmp_path / "structural.npz")
    save_pytree(p3, {"a": np.ones(3)})
    with pytest.raises(ValueError):
        load_pytree(p3, {"a": np.ones(3), "b": np.ones(2)})
    assert os.path.exists(p3), "structural mismatch must not quarantine"


def test_ckpt_corrupt_injection_quarantines_and_restarts(tmp_path):
    params, args = _toy_params(), (_TARGET,)
    clean = run_em_loop(toy_step, params, args, 0.0, 12, guard=True)
    ck = str(tmp_path / "chaos.npz")
    c0 = _counters()
    # 12 iters / every 4 = 3 chunk saves; corrupt the LAST one (earlier
    # corruption would be healed by the atomic rewrite of later chunks)
    with faults.inject("ckpt_corrupt@3"):
        run_em_loop(
            toy_step, params, args, 0.0, 12, guard=True,
            checkpoint_path=ck, checkpoint_every=4,
        )
    res = run_em_loop(
        toy_step, params, args, 0.0, 12, guard=True,
        checkpoint_path=ck, checkpoint_every=4,
    )
    c1 = _counters()
    assert os.path.exists(ck + ".corrupt")
    assert c1["checkpoint.quarantined"] == c0.get(
        "checkpoint.quarantined", 0) + 1
    assert _delta(res.params, clean.params) == 0.0
    assert res.n_iter == clean.n_iter


def test_preemption_resume_bit_identical(tmp_path):
    params, args = _toy_params(), (_TARGET,)
    clean = run_em_loop(toy_step, params, args, 0.0, 12, guard=True)
    ck = str(tmp_path / "preempt.npz")
    with pytest.raises(faults.SimulatedPreemption):
        with faults.inject("preempt@1"):
            run_em_loop(
                toy_step, params, args, 0.0, 12, guard=True,
                checkpoint_path=ck, checkpoint_every=4,
            )
    res = run_em_loop(
        toy_step, params, args, 0.0, 12, guard=True,
        checkpoint_path=ck, checkpoint_every=4,
    )
    assert res.n_iter == clean.n_iter
    for a, b in zip(jax.tree.leaves(res.params), jax.tree.leaves(clean.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# satellite: eigh-pinv non-finite error; unguarded program stability
# ---------------------------------------------------------------------------


def test_solve_normal_nonfinite_error_message():
    from dynamic_factor_models_tpu.ops.linalg import solve_normal

    A = jnp.eye(3).at[1, 1].set(jnp.nan)
    b = jnp.ones(3)
    with pytest.raises(ValueError, match="non-finite.*normal equations"):
        solve_normal(A, b)
    with pytest.raises(ValueError, match="pinv"):
        solve_normal(jnp.eye(3), b.at[0].set(jnp.inf))
    # finite inputs still solve
    np.testing.assert_allclose(
        np.asarray(solve_normal(2.0 * jnp.eye(3), b)), np.full(3, 0.5),
        atol=1e-12,
    )


def test_chol_guarded_flags_failure_without_nan():
    from dynamic_factor_models_tpu.ops.linalg import chol_guarded

    L, ok = chol_guarded(jnp.eye(3) * 4.0)
    assert bool(ok)
    np.testing.assert_allclose(np.asarray(L), 2.0 * np.eye(3), atol=1e-12)
    L, ok = chol_guarded(-jnp.eye(3))  # indefinite: factorization fails
    assert not bool(ok)
    assert bool(jnp.all(jnp.isfinite(L))), "guarded factor must be scrubbed"


def test_unguarded_program_unchanged_by_guarded_machinery():
    """The DFM_GUARDS=0 program is the pre-guardrail program: its
    stableHLO is byte-identical before and after the guarded twin
    compiles, runs, and trips its ladder."""
    from dynamic_factor_models_tpu.models.emloop import (
        _em_while_jit,
        _fresh_carry,
    )
    from dynamic_factor_models_tpu.utils.compile import donation_enabled

    params, args = _toy_params(), (_TARGET,)

    def _hlo():
        tol_arr = jnp.asarray(1e-9, jnp.result_type(float))
        carry = _fresh_carry(params, tol_arr, 20)
        return _em_while_jit(donation_enabled()).lower(
            toy_step, carry, args, tol_arr, 20,
            jnp.asarray(20, jnp.int32), 0,
        ).as_text()

    before = _hlo()
    with faults.inject("nan_estep@2"):
        run_em_loop(toy_step, params, args, 1e-9, 20, guard=True)
    run_em_loop(toy_step_diverging, params, args, 1e-9, 20, guard=True)
    assert _hlo() == before


# ---------------------------------------------------------------------------
# full-scale drills (slow lane): real entry points end to end
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_mf_nan_under_period3_mask_demotes_and_matches_sequential():
    """Satellite drill: a persistent NaN E-step injected into the
    SQUAREM-accelerated mixed-frequency fit must (1) survive, (2) recover
    via the demote ("sequential") rung after both jitter retries re-trip,
    and (3) land within 1e-8 of the clean sequential run — injection at
    iteration 1 makes last-good the initial params, on which the jitter
    repair is an exact no-op, so the demoted run replays the sequential
    trajectory bit for bit.  steady_gains must gate off NaN params and
    accept the recovered ones."""
    from dynamic_factor_models_tpu.models.mixed_freq import (
        estimate_mixed_freq_dfm,
        steady_gains,
    )

    rng = np.random.default_rng(0)
    T, N = 60, 8
    f = rng.standard_normal((T, 1))
    lam = rng.standard_normal((N, 1))
    x = f @ lam.T + 0.5 * rng.standard_normal((T, N))
    xq = x.copy()
    is_q = np.zeros(N, bool)
    is_q[-2:] = True
    xq[:, -2:] = np.nan
    xq[2::3, -2:] = x[2::3, -2:]  # quarter-end months: the period-3 mask

    clean = estimate_mixed_freq_dfm(
        xq, is_q, r=1, p=5, max_em_iter=40, tol=1e-7
    )
    with faults.inject("nan_estep@1+"):
        res = estimate_mixed_freq_dfm(
            xq, is_q, r=1, p=5, max_em_iter=40, tol=1e-7, accel="squarem"
        )
    assert res.health == guards.HEALTH_OK
    assert _delta(res.params, clean.params) < 1e-8

    # the recovered params feed the periodic-DARE gain set; NaN params
    # must be rejected before the Riccati recursion can propagate them
    gains = steady_gains(res.params)
    assert gains is not None
    with pytest.raises(ValueError, match="non-finite"):
        steady_gains(res.params._replace(Q=res.params.Q * np.nan))


@pytest.mark.slow
def test_ssm_entry_point_reports_fault_telemetry():
    """estimate_dfm_em end to end with an injected fault: the run
    completes healthy, the results carry converged/health, and the
    RunRecord surfaces the fault counters."""
    from dynamic_factor_models_tpu.models.ssm import DFMConfig, estimate_dfm_em

    rng = np.random.default_rng(1)
    T, N, r = 50, 7, 1
    x = (rng.standard_normal((T, r)) @ rng.standard_normal((r, N))
         + 0.5 * rng.standard_normal((T, N)))
    cfg = DFMConfig(nfac_u=r, n_factorlag=1)

    telemetry.enable()
    try:
        clean = estimate_dfm_em(x, np.ones(N), 0, T - 1, cfg, max_em_iter=25)
        with faults.inject("nan_estep@2"):
            res = estimate_dfm_em(x, np.ones(N), 0, T - 1, cfg, max_em_iter=25)
        recs = [
            r_ for r_ in telemetry.records()
            if r_.get("entry") == "estimate_dfm_em"
            and r_.get("faults_detected")
        ]
    finally:
        telemetry.disable()
    assert res.health == guards.HEALTH_OK
    assert res.converged == clean.converged
    assert _delta(res.params, clean.params) < 1e-8
    assert recs, "entry-point RunRecord must surface fault counters"
    assert recs[-1]["faults_detected"] == 1
    assert recs[-1]["recoveries"] == 1
    assert recs[-1]["final_health"] == "ok"
