"""Exercise the multi-host path of parallel/distributed.py for real: two OS
processes (4 virtual CPU devices each) joined by `jax.distributed.initialize`
into one 8-device runtime, with cross-process collectives over Gloo — the
CPU-world analogue of two TPU hosts over DCN (SURVEY.md section 5.8).

The single-process no-op branch is covered in test_favar_parallel; this file
covers the branch that previously existed only on trust: coordination-service
startup, the process-major outer mesh axis, cross-process psum, and the
replication-sharded bootstrap on a global mesh with identical SPMD results on
every process.
"""

import os
import socket
import subprocess
import sys
import time

import pytest

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_dist_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_runtime_agrees(tmp_path):
    port, nproc = _free_port(), 2
    env = {
        k: v
        for k, v in os.environ.items()
        # each worker configures its own platform/device-count flags
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    # stdout/stderr go to files, not pipes: if one worker dies at the
    # distributed barrier the other blocks, and we still want the dead
    # worker's traceback (pipes also deadlock when one fills while the
    # other is being waited on)
    logs = [(tmp_path / f"w{i}.out", tmp_path / f"w{i}.err") for i in range(nproc)]
    procs = []
    try:
        for i in range(nproc):
            with open(logs[i][0], "w") as out, open(logs[i][1], "w") as err:
                procs.append(
                    subprocess.Popen(
                        [sys.executable, _WORKER, str(i), str(nproc), str(port)],
                        stdout=out,
                        stderr=err,
                        env=env,
                    )
                )
        deadline = time.monotonic() + 600
        while any(p.poll() is None for p in procs):
            # a dead worker strands the others at the barrier: kill the
            # rest immediately instead of burning the whole deadline
            if any(p.poll() not in (None, 0) for p in procs):
                break
            if time.monotonic() > deadline:
                break
            time.sleep(0.5)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()

    dumps = [
        f"worker {i}: rc={p.returncode}\n{logs[i][0].read_text()}"
        f"\n{logs[i][1].read_text()[-2000:]}"
        for i, p in enumerate(procs)
    ]
    assert all(p.returncode == 0 for p in procs), "\n\n".join(dumps)
    results = sorted(
        line
        for o, _ in logs
        for line in o.read_text().splitlines()
        if line.startswith("RESULT")
    )
    assert len(results) == nproc, "\n\n".join(dumps)
    # SPMD: every process must report identical psum and bootstrap quantiles
    payloads = {r.split("pid=")[1].split(" ", 1)[1] for r in results}
    assert len(payloads) == 1, f"processes disagree: {results}"
