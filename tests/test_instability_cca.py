"""Golden tests for Table 4 (Chow/QLR instability), Table 5 (FAVAR CCA), and
the Figure-7 constrained-loading path."""

import numpy as np
import pytest

from dynamic_factor_models_tpu.models.constraints import construct_constraint
from dynamic_factor_models_tpu.models.dfm import (
    DFMConfig,
    compute_series,
    estimate_dfm,
    estimate_factor,
)
from dynamic_factor_models_tpu.models.favar_instruments import favar_instrument_table
from dynamic_factor_models_tpu.models.instability import instability_scan


@pytest.mark.slow
def test_table4_r4(dataset_all):
    ds = dataset_all
    cfg = DFMConfig(nfac_u=4)
    F_full, _ = estimate_factor(ds.bpdata, ds.inclcode, 2, 223, cfg)
    F_pre, _ = estimate_factor(ds.bpdata, ds.inclcode, 2, 103, cfg)
    F_post, _ = estimate_factor(ds.bpdata, ds.inclcode, 104, 223, cfg)
    res = instability_scan(ds.bpdata, F_full, F_pre, F_post, 104, 4)
    np.testing.assert_allclose(res.chow_rej_ratios, [0.369, 0.534, 0.625], atol=1e-3)
    np.testing.assert_allclose(res.qlr_rej_ratios, [0.619, 0.767, 0.830], atol=1e-3)
    np.testing.assert_allclose(
        res.cor_pre_quantiles, [0.658, 0.888, 0.962, 0.986, 0.996], atol=1e-3
    )


@pytest.fixture(scope="module")
def dfm8_all(dataset_all):
    return estimate_dfm(
        dataset_all.bpdata, dataset_all.inclcode, 2, 223, DFMConfig(nfac_u=8)
    )


def test_table5_set_a(dataset_all, dfm8_all):
    r_res, r_lev = favar_instrument_table(
        dataset_all.bpdata,
        dataset_all.bpnamevec,
        ["GDPC96", "PAYEMS", "PCECTPI", "FEDFUNDS"],
        dfm8_all.factor,
        dfm8_all.var,
        4,
        2,
        223,
    )
    np.testing.assert_allclose(r_res, [0.759, 0.645, 0.595, 0.493], atol=1e-3)
    assert r_lev.shape == (4,) and (r_lev <= 1.0).all()


def test_table5_set_b(dataset_all, dfm8_all):
    r_res, _ = favar_instrument_table(
        dataset_all.bpdata,
        dataset_all.bpnamevec,
        ["GDPC96", "PAYEMS", "PCECTPI", "FEDFUNDS",
         "NAPMPRI", "WPU0561", "CP90_TBILL", "GS10_TB3M"],
        dfm8_all.factor,
        dfm8_all.var,
        4,
        2,
        223,
    )
    assert abs(r_res[0] - 0.829) < 1e-3
    assert abs(r_res[-1] - 0.013) < 1e-3


def test_figure7_unit_loading_constraint(dataset_all):
    """Oil-price DFM: R=I, r=e1 pins the oil loadings to the first factor
    (Stock_Watson.ipynb cells 63-65)."""
    ds = dataset_all
    nfac = 8
    varnames = ["WPU0561", "MCOILWTICO", "MCOILBRENTEU", "RAC_IMP"]
    incl_names = [n for n, c in zip(ds.bpnamevec, ds.inclcode) if c == 1]
    R = np.eye(nfac)
    r = np.eye(nfac)[0]
    res = estimate_dfm(
        ds.bpdata, ds.inclcode, 104, 223, DFMConfig(nfac_u=nfac),
        constraint_factor=construct_constraint(varnames, incl_names, R, r),
        constraint_loading=construct_constraint(varnames, ds.bpnamevec, R, r),
    )
    lam = np.asarray(res.lam)
    e1 = np.eye(nfac)[0]
    for v in varnames:
        np.testing.assert_allclose(lam[ds.bpnamevec.index(v)], e1, atol=1e-8)
    # common component of a constrained series is exactly the first factor
    cc = np.asarray(compute_series(res, ds.bpnamevec.index("WPU0561")))
    f0 = np.asarray(res.factor[:, 0])
    m = np.isfinite(cc) & np.isfinite(f0)
    np.testing.assert_allclose(cc[m], f0[m], atol=1e-10)
