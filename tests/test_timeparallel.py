"""Parallel-in-time EM (PR-16): fused collapsed filter/smoother elements,
blocked time slabs over the mesh "time" axis, and the 3-D
hosts x time x series mesh.

Coverage map:

* fused element construction — `em_step_assoc_fused` built from the
  collapsed per-step payload (O(r^3) per element, no N-sized operand)
  matches both the unfused associative step and the sequential stats
  step, and the public ``"ssm.assoc"`` alias auto-dispatches to the
  fused body above `LARGE_N_THRESHOLD`;
* `parallel.timescan.sharded_scan` edge cases on the forced-8-device CPU
  platform — non-power-of-two T, T % n_dev != 0 (end-padding with inert
  repeats), and single-block degeneracy — at 1e-12 against
  ``jax.lax.associative_scan``, for both local recursion kinds;
* the time-parallel EM steps resolved through the transform stack
  (`em_step_tp_b*`, `em_step_tp_b*_d*`, `em_step_ar_tp_b*`) at 1e-10
  against the sequential references, including the full estimators
  `estimate_dfm_em(t_blocks=...)` / `estimate_dfm_em_ar(t_blocks=...)`
  (params, loglik path, E-step moments via the factor paths);
* stack refusals (time x steady, time x batch, AR time x shard, AR time
  without collapse), the derived AOT plan entries, warm-process registry
  hits, and the telemetry device-column rendering for 3-D meshes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models import ssm, transforms as tfm
from dynamic_factor_models_tpu.models import pkalman as pk
from dynamic_factor_models_tpu.models.dfm import DFMConfig
from dynamic_factor_models_tpu.models.ssm import (
    SSMParams,
    _collapse_obs,
    compute_panel_stats,
    em_step_assoc,
    em_step_assoc_fused,
    em_step_stats,
    estimate_dfm_em,
)
from dynamic_factor_models_tpu.models.ssm_ar import (
    SSMARParams,
    compute_qd_stats,
    em_step_ar_qd,
    estimate_dfm_em_ar,
)
from dynamic_factor_models_tpu.parallel import data_mesh, sharded_scan
from dynamic_factor_models_tpu.utils import compile as cc
from dynamic_factor_models_tpu.utils.telemetry import _dev_str

PARITY_ATOL = 1e-10  # acceptance bar vs the sequential reference
SCAN_ATOL = 1e-12  # acceptance bar for the raw scan itself


def _panel(T=67, N=12, r=3, p=2, miss=0.15, seed=0):
    rng = np.random.default_rng(seed)
    lam = jnp.asarray(0.5 * rng.standard_normal((N, r)))
    A = jnp.zeros((p, r, r)).at[0].set(0.3 * jnp.eye(r))
    params = SSMParams(lam, jnp.ones(N) * 0.7, A, jnp.eye(r))
    x = jnp.asarray(rng.standard_normal((T, N)))
    mask = jnp.asarray(rng.random((T, N)) > miss)
    return params, jnp.where(mask, x, 0.0), mask.astype(x.dtype)


@pytest.fixture(scope="module")
def problem():
    return _panel()


def _assert_leaves_close(a, b, atol=PARITY_ATOL):
    for u, v in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(
            np.asarray(u), np.asarray(v), atol=atol
        )


# ---------------------------------------------------------------------------
# 1. 3-D mesh topology
# ---------------------------------------------------------------------------


@pytest.mark.timeparallel
@pytest.mark.multidevice
def test_data_mesh_time_axis_topology():
    m = data_mesh(2, hosts=1, t_blocks=4)
    assert m.axis_names == ("dcn", "time", "ici")
    assert m.devices.shape == (1, 4, 2)
    # flat-mesh byte-identity when no time axis is requested
    flat = data_mesh(8)
    assert data_mesh(8, t_blocks=0).axis_names == flat.axis_names
    assert [d.id for d in data_mesh(8, t_blocks=1).devices.ravel()] == [
        d.id for d in flat.devices.ravel()
    ]
    # same device set, process-major order
    assert sorted(d.id for d in m.devices.ravel()) == [
        d.id for d in flat.devices.ravel()
    ]


@pytest.mark.timeparallel
def test_data_mesh_time_axis_validation():
    with pytest.raises(ValueError):
        data_mesh(jax.device_count(), t_blocks=3)  # does not divide
    with pytest.raises(ValueError):
        data_mesh(jax.device_count() * 2, t_blocks=2)  # too many devices


# ---------------------------------------------------------------------------
# 2. fused collapsed elements (the retired unfused ssm.assoc)
# ---------------------------------------------------------------------------


@pytest.mark.timeparallel
def test_fused_elements_match_unfused_assoc(problem):
    params, x, mask = problem
    p1, ll1 = em_step_assoc(params, x, mask)
    p2, ll2 = em_step_assoc_fused(params, x, mask)
    np.testing.assert_allclose(float(ll1), float(ll2), rtol=1e-10)
    _assert_leaves_close(p1, p2)


@pytest.mark.timeparallel
def test_fused_elements_match_sequential(problem):
    params, x, mask = problem
    stats = compute_panel_stats(x, mask)
    p0, ll0 = em_step_stats(params, x, mask, stats)
    p2, ll2 = em_step_assoc_fused(params, x, mask)
    np.testing.assert_allclose(float(ll0), float(ll2), rtol=1e-10)
    _assert_leaves_close(p0, p2)


@pytest.mark.timeparallel
def test_assoc_alias_dispatches_fused_above_threshold(problem, monkeypatch):
    """The public "ssm.assoc" name keeps ONE entry point: the step
    dispatches the fused element builder whenever N clears
    LARGE_N_THRESHOLD (static shape test, free inside jit).  Lowering
    the threshold under the panel width forces the fused branch through
    the SAME alias and must not move the answer."""
    params, x, mask = problem
    res = tfm.resolve(tfm.Stack("ssm.assoc"))
    p_small, ll_small = res.step(params, x, mask)
    monkeypatch.setattr(ssm, "LARGE_N_THRESHOLD", 4)
    jax.clear_caches()  # drop the traced branch, force a re-trace
    try:
        p_big, ll_big = res.step(params, x, mask)
    finally:
        jax.clear_caches()
    np.testing.assert_allclose(float(ll_small), float(ll_big), rtol=1e-10)
    _assert_leaves_close(p_small, p_big)


# ---------------------------------------------------------------------------
# 3. sharded_scan edge cases (forced 8-device CPU)
# ---------------------------------------------------------------------------


def _scan_problem(T, seed=2):
    rng = np.random.default_rng(seed)
    elems = (
        jnp.asarray(rng.standard_normal((T, 3, 3))) * 0.1,
        jnp.asarray(rng.standard_normal((T, 3))),
    )

    def comb(a, b):
        return (
            jnp.einsum("...ij,...jk->...ik", b[0], a[0]),
            jnp.einsum("...ij,...j->...i", b[0], a[1]) + b[1],
        )

    return comb, elems


@pytest.mark.timeparallel
@pytest.mark.multidevice
@pytest.mark.parametrize("T", [67, 63, 96])
@pytest.mark.parametrize("local", ["associative", "sequential"])
def test_sharded_scan_edge_lengths(T, local):
    """Non-power-of-two T and T % n_dev != 0: end-padding with repeats of
    the last element is causally inert for an inclusive forward scan, so
    positions [:T] match the unsharded scan at 1e-12 — eager AND jitted
    (the jit path exercises the partitioner firewall)."""
    comb, elems = _scan_problem(T)
    mesh = data_mesh(1, hosts=1, t_blocks=8)
    ref = jax.lax.associative_scan(comb, elems)
    out = sharded_scan(comb, elems, mesh, local=local)
    _assert_leaves_close(out, ref, atol=SCAN_ATOL)
    jout = jax.jit(
        lambda e: sharded_scan(comb, e, mesh, local=local)
    )(elems)
    _assert_leaves_close(jout, ref, atol=SCAN_ATOL)


@pytest.mark.timeparallel
def test_sharded_scan_single_block_degeneracy():
    """A size-1 time axis must fall through to the plain local scan —
    no collective, no padding, any T.  (data_mesh(t_blocks=1) returns
    the FLAT mesh by byte-identity design, so the size-1 axis is built
    explicitly here.)"""
    from jax.sharding import Mesh

    comb, elems = _scan_problem(61)
    mesh = Mesh(
        np.array(jax.devices()[:1]).reshape(1, 1, 1),
        ("dcn", "time", "ici"),
    )
    ref = jax.lax.associative_scan(comb, elems)
    for local in ("associative", "sequential"):
        out = sharded_scan(comb, elems, mesh, local=local)
        _assert_leaves_close(out, ref, atol=SCAN_ATOL)


@pytest.mark.timeparallel
def test_sharded_scan_rejects_unknown_local_kind():
    from jax.sharding import Mesh

    comb, elems = _scan_problem(8)
    mesh = Mesh(
        np.array(jax.devices()[:1]).reshape(1, 1, 1),
        ("dcn", "time", "ici"),
    )
    with pytest.raises(ValueError, match="local"):
        sharded_scan(comb, elems, mesh, local="recursive")


@pytest.mark.timeparallel
@pytest.mark.multidevice
def test_sharded_scan_on_filter_elements(problem):
    """The production combine (combine_filter on collapsed-built
    FilterElements) through the blocked-slab exchange, ragged T."""
    params, x, mask = problem
    C, b, *_ = _collapse_obs(params.lam, params.R, x, mask)
    elems = pk.filter_elements_collapsed(params, C, b)
    ref = jax.lax.associative_scan(pk.combine_filter, elems)
    mesh = data_mesh(1, hosts=1, t_blocks=8)
    out = sharded_scan(
        pk.combine_filter, elems, mesh, local="sequential"
    )
    _assert_leaves_close(out, ref, atol=SCAN_ATOL)


# ---------------------------------------------------------------------------
# 4. time-parallel EM steps through the transform stack
# ---------------------------------------------------------------------------


@pytest.mark.timeparallel
@pytest.mark.multidevice
def test_em_step_tp_matches_sequential(problem):
    params, x, mask = problem
    stats = compute_panel_stats(x, mask)
    p0, ll0 = em_step_stats(params, x, mask, stats)
    res = tfm.resolve(tfm.Stack("ssm", (tfm.time_shard(8),)))
    assert res.t_blocks == 8
    p1, ll1 = res.step(params, x, mask, stats)
    np.testing.assert_allclose(float(ll0), float(ll1), rtol=1e-10)
    _assert_leaves_close(p0, p1)


@pytest.mark.timeparallel
@pytest.mark.multidevice
def test_em_step_tp_sharded_matches_sequential(problem):
    """time x shard on the 3-D mesh: 4 time blocks x 2 series shards."""
    from dynamic_factor_models_tpu.parallel.mesh import series_pad
    from dynamic_factor_models_tpu.utils.compile import (
        pad_panel,
        pad_ssm_params,
        unpad_ssm_params,
    )

    params, x, mask = problem
    T, N = x.shape
    stats = compute_panel_stats(x, mask)
    p0, ll0 = em_step_stats(params, x, mask, stats)

    Npad = series_pad(N, 2)
    xb, mb, tw = pad_panel(x, mask, T, Npad)
    stats_b = compute_panel_stats(xb, mb)._replace(tw=tw)
    res = tfm.resolve(
        tfm.Stack("ssm", (tfm.time_shard(4), tfm.shard(2)))
    )
    p1, ll1 = res.step(pad_ssm_params(params, Npad), xb, mb, stats_b)
    np.testing.assert_allclose(float(ll0), float(ll1), rtol=1e-10)
    p1u = unpad_ssm_params(jax.tree.map(np.asarray, p1), N)
    _assert_leaves_close(p0, p1u)


@pytest.mark.timeparallel
@pytest.mark.multidevice
def test_em_step_ar_tp_matches_sequential(problem):
    params, x, mask = problem
    N = x.shape[1]
    arp = SSMARParams(
        params.lam, jnp.zeros(N), jnp.ones(N) * 0.5, params.A, params.Q
    )
    qd = compute_qd_stats(x, mask)
    p0, ll0 = em_step_ar_qd(arp, x, qd)
    res = tfm.resolve(
        tfm.Stack("ar", (tfm.collapse(), tfm.time_shard(8)))
    )
    p1, ll1 = res.step(arp, x, qd)
    np.testing.assert_allclose(float(ll0), float(ll1), rtol=1e-10)
    _assert_leaves_close(p0, p1)


# ---------------------------------------------------------------------------
# 5. stack refusals and step naming
# ---------------------------------------------------------------------------


@pytest.mark.timeparallel
def test_time_shard_refusals():
    cases = [
        (tfm.Stack("ssm", (tfm.time_shard(4), tfm.steady_tail(16))),
         "steady"),
        (tfm.Stack("ssm", (tfm.time_shard(4), tfm.batch(2))), "batch"),
        (tfm.Stack(
            "ar", (tfm.collapse(), tfm.time_shard(4), tfm.shard(2))
        ), "collapse"),
        (tfm.Stack("ar", (tfm.time_shard(4),)), "collapsed"),
    ]
    for stack, frag in cases:
        with pytest.raises(ValueError, match=frag):
            tfm.resolve(stack)
    with pytest.raises(ValueError, match="t_blocks > 1"):
        tfm.resolve(tfm.Stack("ssm", (tfm.time_shard(1),)))


@pytest.mark.timeparallel
@pytest.mark.multidevice
def test_tp_step_names_are_aot_stable():
    """The lru_cached factories name their steps by (blocks, shards) so
    the AOT registry's statics key is process-stable."""
    from dynamic_factor_models_tpu.models import emtime

    assert emtime.em_step_tp_for(8).__wrapped__.__name__ == "em_step_tp_b8"
    assert (
        emtime.em_step_tp_for(4, 2).__wrapped__.__name__
        == "em_step_tp_b4_d2"
    )
    assert (
        emtime.em_step_ar_tp_for(8).__wrapped__.__name__
        == "em_step_ar_tp_b8"
    )
    # same (blocks, shards) -> the SAME jitted callable (cache hit)
    assert emtime.em_step_tp_for(8) is emtime.em_step_tp_for(8)
    with pytest.raises(ValueError):
        emtime.em_step_tp_for(1)


# ---------------------------------------------------------------------------
# 6. full estimators
# ---------------------------------------------------------------------------


def _estimation_panel(T=90, N=12, r=2, seed=0):
    rng = np.random.default_rng(seed)
    f = np.zeros((T, r))
    for t in range(1, T):
        f[t] = 0.6 * f[t - 1] + rng.standard_normal(r)
    lam = rng.standard_normal((N, r))
    x = f @ lam.T + 0.6 * rng.standard_normal((T, N))
    miss = rng.random((T, N)) < 0.1
    miss[:, N // 2:] = False  # keep PCA-initializable series
    x[miss] = np.nan
    return x


@pytest.mark.timeparallel
@pytest.mark.multidevice
def test_estimate_dfm_em_t_blocks_parity():
    x = _estimation_panel()
    T, N = x.shape
    cfg = DFMConfig(nfac_u=2, tol=0.0, max_iter=300)
    base = estimate_dfm_em(x, np.ones(N), 0, T - 1, cfg, max_em_iter=8)
    tp = estimate_dfm_em(
        x, np.ones(N), 0, T - 1, cfg, max_em_iter=8, t_blocks=4
    )
    np.testing.assert_allclose(
        np.asarray(tp.loglik_path), np.asarray(base.loglik_path),
        atol=PARITY_ATOL,
    )
    np.testing.assert_allclose(
        np.asarray(tp.factors), np.asarray(base.factors),
        atol=PARITY_ATOL,
    )
    # E-step moments: the smoothed factor covariances ride the result
    np.testing.assert_allclose(
        np.asarray(tp.factor_covs), np.asarray(base.factor_covs),
        atol=PARITY_ATOL,
    )
    _assert_leaves_close(tp.params, base.params)


@pytest.mark.timeparallel
@pytest.mark.multidevice
def test_estimate_dfm_em_t_blocks_times_shards_parity():
    x = _estimation_panel(seed=1)
    T, N = x.shape
    cfg = DFMConfig(nfac_u=2, tol=0.0, max_iter=300)
    base = estimate_dfm_em(x, np.ones(N), 0, T - 1, cfg, max_em_iter=6)
    tp = estimate_dfm_em(
        x, np.ones(N), 0, T - 1, cfg, max_em_iter=6,
        t_blocks=4, n_shards=2,
    )
    np.testing.assert_allclose(
        np.asarray(tp.loglik_path), np.asarray(base.loglik_path),
        atol=PARITY_ATOL,
    )
    np.testing.assert_allclose(
        np.asarray(tp.factors), np.asarray(base.factors),
        atol=PARITY_ATOL,
    )


@pytest.mark.timeparallel
def test_estimate_dfm_em_t_blocks_validation():
    x = _estimation_panel()
    T, N = x.shape
    cfg = DFMConfig(nfac_u=2, tol=0.0, max_iter=300)
    with pytest.raises(ValueError, match="method"):
        estimate_dfm_em(
            x, np.ones(N), 0, T - 1, cfg, max_em_iter=2,
            t_blocks=4, method="associative",
        )
    with pytest.raises(ValueError, match="gram_dtype"):
        estimate_dfm_em(
            x, np.ones(N), 0, T - 1, cfg, max_em_iter=2,
            t_blocks=4, gram_dtype="bfloat16",
        )
    with pytest.raises(ValueError, match="device"):
        estimate_dfm_em(
            x, np.ones(N), 0, T - 1, cfg, max_em_iter=2,
            t_blocks=jax.device_count() * 2,
        )


@pytest.mark.timeparallel
@pytest.mark.multidevice
def test_estimate_dfm_em_ar_t_blocks_parity():
    # complete panel: the collapsed AR path's exact mask class
    x = _estimation_panel(seed=2)
    x = np.nan_to_num(x)
    T, N = x.shape
    cfg = DFMConfig(nfac_u=2, tol=0.0, max_iter=300)
    base = estimate_dfm_em_ar(
        x, np.ones(N), 0, T - 1, cfg, max_em_iter=8, method="collapsed"
    )
    tp = estimate_dfm_em_ar(
        x, np.ones(N), 0, T - 1, cfg, max_em_iter=8,
        method="collapsed", t_blocks=8,
    )
    np.testing.assert_allclose(
        np.asarray(tp.loglik_path), np.asarray(base.loglik_path),
        atol=PARITY_ATOL,
    )
    np.testing.assert_allclose(
        np.asarray(tp.factors), np.asarray(base.factors),
        atol=PARITY_ATOL,
    )


@pytest.mark.timeparallel
def test_estimate_dfm_em_ar_t_blocks_validation():
    x = np.nan_to_num(_estimation_panel())
    T, N = x.shape
    cfg = DFMConfig(nfac_u=2, tol=0.0, max_iter=300)
    with pytest.raises(ValueError, match="collapsed"):
        estimate_dfm_em_ar(
            x, np.ones(N), 0, T - 1, cfg, max_em_iter=2,
            method="dense", t_blocks=4,
        )


# ---------------------------------------------------------------------------
# 7. derived AOT plan + warm-process registry hits
# ---------------------------------------------------------------------------


@pytest.mark.timeparallel
def test_enumerate_stacks_time_entries():
    base = cc.CompileSpec(
        T=60, N=12, r=2, p=1, dtype=str(np.dtype(float)), max_em_iter=4
    )
    n0 = len(tfm.enumerate_stacks(base))
    # t_blocks alone adds nothing: the tp kernels are opt-in by name
    silent = cc.CompileSpec(
        T=60, N=12, r=2, p=1, dtype=str(np.dtype(float)),
        max_em_iter=4, t_blocks=4,
    )
    assert len(tfm.enumerate_stacks(silent)) == n0
    tp = cc.CompileSpec(
        T=60, N=12, r=2, p=1, dtype=str(np.dtype(float)),
        max_em_iter=4, t_blocks=4, n_shards=2,
        kernels=cc.CompileSpec.kernels
        + ("em_step_tp", "em_step_ar_tp", "em_step_tp_sharded"),
    )
    keys = [e.key for e in tfm.enumerate_stacks(tp)]
    assert keys.count("em_step_tp") == 1
    assert keys.count("em_step_ar_tp") == 1
    assert keys.count("em_step_tp_sharded") == 1
    # ... and without n_shards the time x shard product is NOT derived
    tp_only = cc.CompileSpec(
        T=60, N=12, r=2, p=1, dtype=str(np.dtype(float)),
        max_em_iter=4, t_blocks=4,
        kernels=cc.CompileSpec.kernels
        + ("em_step_tp", "em_step_ar_tp", "em_step_tp_sharded"),
    )
    keys = [e.key for e in tfm.enumerate_stacks(tp_only)]
    assert "em_step_tp_sharded" not in keys


@pytest.mark.timeparallel
@pytest.mark.multidevice
def test_em_step_tp_precompile_warm_hit():
    """The derived plan compiles em_step_tp ahead of time; a second
    precompile of the identical spec is served entirely from the
    in-process registry (zero XLA work) — the warm-process acceptance
    pin for the time-parallel kernels."""
    cc.reset_counters()
    spec = cc.CompileSpec(
        T=90, N=12, r=2, p=1, dtype=str(np.dtype(float)),
        max_em_iter=4, t_blocks=4, kernels=("em_step_tp",),
    )
    r1 = cc.precompile(spec)
    assert not r1["kernels"]["em_step_tp"]["aot_cached"]
    assert cc.counters()["em_step_tp"]["compiles"] == 1
    r2 = cc.precompile(spec)
    assert r2["kernels"]["em_step_tp"]["aot_cached"]
    assert r2["compile_s_total"] == 0.0
    c = cc.counters()["em_step_tp"]
    assert c["compiles"] == 1 and c["aot_hits"] == 1


# ---------------------------------------------------------------------------
# 8. telemetry rendering
# ---------------------------------------------------------------------------


@pytest.mark.timeparallel
@pytest.mark.telemetry
def test_dev_str_renders_any_mesh_rank():
    assert _dev_str({"mesh_shape": [8], "sharded": True}) == "8"
    assert _dev_str({"mesh_shape": [2, 4], "sharded": True}) == "2x4"
    # 3-D time mesh: renders WITHOUT the sharded flag (time-only runs
    # shard no series axis)
    assert _dev_str({"mesh_shape": [1, 4, 2]}) == "1x4x2"
    assert _dev_str({"mesh_shape": [1, 8, 1], "sharded": False}) == "1x8x1"
    assert _dev_str({"n_devices": 8, "sharded": True}) == "8"
    assert _dev_str({"n_devices": 8}) == "-"
    assert _dev_str({}) == "-"


@pytest.mark.timeparallel
@pytest.mark.telemetry
def test_run_record_defaults_t_blocks(tmp_path, monkeypatch):
    import json

    from dynamic_factor_models_tpu.utils import telemetry as T

    path = tmp_path / "runs.jsonl"
    monkeypatch.setenv("DFM_TELEMETRY", str(path))
    monkeypatch.setattr(T, "_explicit_enabled", None)
    monkeypatch.setattr(T, "_explicit_sink", None)

    with T.RunRecord("em_tp_test", {}) as rec:
        rec.set(T=10, N=4)
    rec_d = json.loads(path.read_text().strip().splitlines()[-1])
    assert rec_d["t_blocks"] == 0  # sequential default, field present

    with T.RunRecord("em_tp_test", {}) as rec:
        rec.set(t_blocks=4, mesh_shape=[1, 4, 1])
    rec_d = json.loads(path.read_text().strip().splitlines()[-1])
    assert rec_d["t_blocks"] == 4
    assert _dev_str(rec_d) == "1x4x1"
