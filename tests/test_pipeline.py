"""Async pipelined serving: double-buffered rounds, bounded admission,
tenant-sharded workers (PR: async pipelined serving).

Pinned claims:

1. pipelined rounds are BITWISE equal to sequential `flush_period`
   serving — serial, threaded, and manual backstages all land every
   tenant on the identical FilterState;
2. the stage-handoff structure is explicit and bounded: at most `slots`
   rounds in flight (two-slot ring), back halves commit strictly FIFO
   by round index, and a tenant is in at most ONE in-flight round
   (exclusion), so the crash analysis stays per-round per-tenant;
3. `interleavings()` ENUMERATES every legal stage ordering of the
   two-slot ring — 3 schedules for 2 rounds — and a manual-backstage
   pipeline driven through each schedule produces bit-identical final
   states: overlap is timing-independent by construction, not by luck;
4. the admission front sheds with TYPES: a full bounded queue (or an
   injected ``queue_full@n``) answers a ``queue_full`` system fault,
   and entries whose deadline burned down while queued are shed at
   round formation without dispatching — both countered
   (``serving.admission.shed.*``) and both still one-Response-per-
   submission through `poll()`;
5. kill-matrix at EVERY stage boundary (admit / dispatch / journal /
   commit, every round) and at every `crash_io@n` store site: restart
   recovers, per tenant, acked ≤ recovered ≤ acked + 1 ticks, a second
   restart is bit-identical, and no journal is ever quarantined;
6. with a pipeline attached the every-1024-requests metrics flush runs
   on the COMMIT stage, not the admission path;
7. `TenantRouter` shards tenants by stable hash across M workers with
   disjoint store partitions; routing, fan-out flush, and
   gang-scheduled refits preserve the single-engine response contract
   (the OS-process backend drill is `slow`-marked).
"""

import glob
import os
import time

import numpy as np
import pytest

from dynamic_factor_models_tpu.serving.engine import ServingEngine
from dynamic_factor_models_tpu.serving.pipeline import (
    BACK_STAGES,
    ServingPipeline,
    interleavings,
)
from dynamic_factor_models_tpu.serving.resilience import RetryPolicy
from dynamic_factor_models_tpu.serving.router import TenantRouter, worker_of
from dynamic_factor_models_tpu.serving.store import worker_partition
from dynamic_factor_models_tpu.utils import faults, flight, telemetry

pytestmark = [pytest.mark.serving, pytest.mark.pipeline]

_POLICY = RetryPolicy(max_retries=2, backoff_base_s=0.0)

T, N = 48, 6


@pytest.fixture(autouse=True)
def _clean_obs():
    telemetry.disable()
    flight.reset()
    yield
    telemetry.disable()
    telemetry._explicit_enabled = None
    flight.reset()


def _panel(seed=0):
    rng = np.random.default_rng(seed)
    f = rng.standard_normal((T, 4)).cumsum(0) * 0.1
    lam = rng.standard_normal((N, 4))
    return f @ lam.T + 0.5 * rng.standard_normal((T, N))


def _engine(store_dir=None, **kw):
    kw.setdefault("retry_policy", _POLICY)
    kw.setdefault("max_em_iter", 5)
    return ServingEngine(store_dir=store_dir, **kw)


def _mk(store_dir, n_tenants, seed=7):
    """One registered seed + (n-1) shared clones: cheap to build, and
    every tenant's state diverges as soon as rows differ."""
    eng = _engine(store_dir)
    eng.register("t0", _panel(seed))
    for i in range(1, n_tenants):
        eng.register_shared(f"t{i}", "t0")
    return eng


def _rows(n, seed=9):
    return np.random.default_rng(seed).standard_normal((n, N))


def _tick(tid, row, **extra):
    return {"kind": "tick", "tenant": tid, "x": row, **extra}


def _states(eng):
    return {
        tid: (np.asarray(eng._tenants[tid].state.s).copy(),
              int(eng._tenants[tid].state.t))
        for tid in eng.tenant_ids()
    }


# ---------------------------------------------------------------------------
# 1. parity: pipelined == sequential, bitwise
# ---------------------------------------------------------------------------


def _workload(n_tenants=4, ticks=3, seed=11):
    rows = np.random.default_rng(seed).standard_normal(
        (ticks, n_tenants, N)
    )
    return [
        _tick(f"t{i}", rows[k, i])
        for k in range(ticks) for i in range(n_tenants)
    ]


@pytest.mark.parametrize("backstage", ["serial", "thread"])
def test_pipeline_parity_with_sequential(tmp_path, backstage):
    reqs = _workload()
    ref = _mk(str(tmp_path / "ref"), 4)
    for r in reqs:
        ref.submit(r)
    ref_out = ref.flush_period()
    assert all(r.ok for r in ref_out)

    eng = _mk(str(tmp_path / backstage), 4)
    with ServingPipeline(eng, backstage=backstage,
                         max_round_lanes=4) as pipe:
        for r in reqs:
            pipe.submit(r)
        out = pipe.drain()
    assert len(out) == len(ref_out) and all(r.ok for r in out)
    # responses come back in submission order with matching tenants
    assert [r.tenant for r in out] == [r["tenant"] for r in reqs]
    ref_states, states = _states(ref), _states(eng)
    assert ref_states.keys() == states.keys()
    for tid, (s, t) in ref_states.items():
        assert states[tid][1] == t
        np.testing.assert_array_equal(states[tid][0], s)


def test_pipeline_storeless_parity():
    """No store: the journal stage degenerates but ordering and results
    must be unchanged."""
    reqs = _workload(n_tenants=3, ticks=2)
    ref = _mk(None, 3)
    for r in reqs:
        ref.submit(r)
    ref_out = ref.flush_period()
    eng = _mk(None, 3)
    with ServingPipeline(eng, backstage="serial", max_round_lanes=3) as p:
        for r in reqs:
            p.submit(r)
        out = p.drain()
    assert all(r.ok for r in out) and len(out) == len(ref_out)
    for tid, (s, _t) in _states(ref).items():
        np.testing.assert_array_equal(_states(eng)[tid][0], s)


# ---------------------------------------------------------------------------
# 2. explicit structure: ring bound, FIFO commits, exclusion
# ---------------------------------------------------------------------------


def test_ring_bound_and_fifo_commit_order(tmp_path):
    events = []
    eng = _mk(str(tmp_path / "s"), 8)
    pipe = ServingPipeline(
        eng, backstage="manual", max_round_lanes=4, slots=2,
        boundary_hook=lambda stage, rnd: events.append((stage, rnd)),
    )
    rows = _rows(8)
    for i in range(8):
        pipe.submit(_tick(f"t{i}", rows[i]))
    assert pipe.pump() == 4
    assert pipe.pump() == 4
    # ring full at slots=2: a third pump must refuse, not buffer
    assert pipe.stats()["inflight"] == 2
    with pytest.raises(RuntimeError, match="ring full"):
        pipe.pump()
    # back halves advance strictly FIFO by round index
    assert pipe.step_back() == (0, "journal")
    assert pipe.step_back() == (0, "commit")
    assert pipe.step_back() == (1, "journal")
    assert pipe.step_back() == (1, "commit")
    out = pipe.poll()
    assert len(out) == 8 and all(r.ok for r in out)
    commit_rounds = [rnd for stage, rnd in events if stage == "commit"]
    assert commit_rounds == [0, 1]
    assert pipe.stats()["max_inflight"] == 2
    pipe.close()


def test_per_tenant_exclusion_across_inflight_rounds(tmp_path):
    eng = _mk(str(tmp_path / "s"), 4)
    pipe = ServingPipeline(eng, backstage="manual", max_round_lanes=8)
    rows = _rows(2)
    for k in range(2):
        for i in range(4):
            pipe.submit(_tick(f"t{i}", rows[k]))
    assert pipe.pump() == 4
    # every queued tenant is in an in-flight round: nothing admissible
    assert pipe.pump() == 0
    assert pipe.depth() == 4  # skipped entries kept their queue slot
    pipe.step_back(), pipe.step_back()  # round 0 retires
    assert pipe.pump() == 4
    pipe.step_back(), pipe.step_back()
    out = pipe.poll()
    assert len(out) == 8 and all(r.ok for r in out)
    # in-flight tenants were pinned, and the pin is released after
    assert eng._admission_pin == set()
    pipe.close()


def test_pipeline_pin_blocks_mid_round_eviction(tmp_path):
    """An in-flight round's tenants must not be evicted by budget
    pressure from the NEXT round's fault-ins."""
    eng = _engine(str(tmp_path / "s"), resident_tenants=2)
    eng.register("t0", _panel())
    for i in range(1, 4):
        eng.register_shared(f"t{i}", "t0")
    pipe = ServingPipeline(eng, backstage="manual", max_round_lanes=2)
    rows = _rows(4)
    pipe.submit(_tick("t0", rows[0]))
    pipe.submit(_tick("t1", rows[1]))
    pipe.submit(_tick("t2", rows[2]))
    pipe.submit(_tick("t3", rows[3]))
    assert pipe.pump() == 2          # round 0: t0, t1 (faulted in + pinned)
    assert {"t0", "t1"} <= eng._admission_pin
    assert pipe.pump() == 2          # round 1 faults t2, t3 in
    # round 0's tenants survived round 1's admission
    assert "t0" in eng._tenants and "t1" in eng._tenants
    for _ in range(4):
        pipe.step_back()
    out = pipe.poll()
    assert len(out) == 4 and all(r.ok for r in out)
    assert eng._admission_pin == set()
    pipe.close()


# ---------------------------------------------------------------------------
# 3. deterministic interleaving enumeration
# ---------------------------------------------------------------------------


def test_interleavings_enumeration():
    ils = list(interleavings(n_rounds=2, slots=2))
    assert len(ils) == 3 and len(set(map(tuple, ils))) == 3
    for il in ils:
        pumped, backed = 0, 0
        for tok in il:
            if tok[0] == "pump":
                assert tok[1] == pumped
                pumped += 1
            else:
                _b, rnd, stage = backed // len(BACK_STAGES), tok[1], tok[2]
                assert rnd == _b and stage == BACK_STAGES[
                    backed % len(BACK_STAGES)
                ]
                assert rnd < pumped  # back half never precedes its pump
                backed += 1
            assert pumped - backed // len(BACK_STAGES) <= 2  # ring bound
        assert pumped == 2 and backed == 2 * len(BACK_STAGES)
    # slots=1 collapses to the strictly sequential schedule
    assert len(list(interleavings(n_rounds=3, slots=1))) == 1


def test_all_interleavings_bitwise_equivalent(tmp_path):
    rows = _rows(8)
    reqs = [_tick(f"t{i}", rows[i]) for i in range(8)]
    finals = []
    for j, il in enumerate(interleavings(n_rounds=2, slots=2)):
        eng = _mk(str(tmp_path / f"m{j}"), 8)
        pipe = ServingPipeline(eng, backstage="manual", max_round_lanes=4)
        for r in reqs:
            pipe.submit(r)
        for tok in il:
            if tok[0] == "pump":
                assert pipe.pump() == 4
            else:
                assert pipe.step_back() == (tok[1], tok[2])
        out = pipe.poll()
        assert len(out) == 8 and all(r.ok for r in out)
        pipe.close()
        finals.append(_states(eng))
    for other in finals[1:]:
        for tid, (s, t) in finals[0].items():
            assert other[tid][1] == t
            np.testing.assert_array_equal(other[tid][0], s)


# ---------------------------------------------------------------------------
# 4. admission front: typed shedding
# ---------------------------------------------------------------------------


def test_queue_full_sheds_typed(tmp_path):
    eng = _mk(str(tmp_path / "s"), 2)
    pipe = ServingPipeline(eng, backstage="serial", max_queue=1)
    rows = _rows(3)
    pipe.submit(_tick("t0", rows[0]))
    pipe.submit(_tick("t1", rows[1]))  # queue at capacity: shed
    out = pipe.drain()
    assert len(out) == 2
    assert out[0].ok
    shed = out[1]
    assert not shed.ok and shed.error.category == "system_fault"
    assert shed.error.code == "queue_full" and shed.tenant == "t1"
    assert pipe.stats()["shed_queue_full"] == 1
    # the shed tenant never ticked
    assert int(eng._tenants["t1"].state.t) == T
    pipe.close()


def test_queue_full_fault_injection(tmp_path):
    eng = _mk(str(tmp_path / "s"), 2)
    pipe = ServingPipeline(eng, backstage="serial", max_queue=1024)
    with faults.inject("queue_full@1"):
        pipe.submit(_tick("t0", _rows(1)[0]))
    out = pipe.drain()
    assert len(out) == 1 and not out[0].ok
    assert out[0].error.code == "queue_full"  # forced despite empty queue
    pipe.close()


def test_deadline_shed_at_round_formation(tmp_path):
    eng = _mk(str(tmp_path / "s"), 2)
    pipe = ServingPipeline(eng, backstage="serial")
    rows = _rows(2)
    pipe.submit(_tick("t0", rows[0], deadline_s=0.001))
    pipe.submit(_tick("t1", rows[1]))
    time.sleep(0.01)  # t0's budget burns down while queued
    out = pipe.drain()
    assert len(out) == 2
    assert not out[0].ok and out[0].error.code == "deadline_exceeded"
    assert out[1].ok
    assert pipe.stats()["shed_deadline"] == 1
    # shed at FORMATION: the expired entry never dispatched or journaled
    assert int(eng._tenants["t0"].state.t) == T
    pipe.close()


def test_stall_commit_drill(tmp_path):
    """stall_commit@n sleeps the n-th committing round past its budget:
    acks are DELAYED, never dropped — the lanes were already durable."""
    eng = _mk(str(tmp_path / "s"), 2)
    pipe = ServingPipeline(eng, backstage="serial", max_round_lanes=2)
    rows = _rows(2)
    t0 = time.perf_counter()
    with faults.inject("stall_commit@1"):
        pipe.submit(_tick("t0", rows[0]))
        pipe.submit(_tick("t1", rows[1]))
        out = pipe.drain()
    assert time.perf_counter() - t0 >= 0.02  # the injected stall
    assert len(out) == 2 and all(r.ok for r in out)
    assert int(eng._tenants["t0"].state.t) == T + 1
    pipe.close()


def test_admission_gauges_and_shed_counters(tmp_path, monkeypatch):
    monkeypatch.delenv("DFM_TELEMETRY", raising=False)
    sink = str(tmp_path / "t.jsonl")
    telemetry.enable(sink=sink)
    telemetry.reset()  # counters are process-global
    eng = _mk(str(tmp_path / "s"), 2)
    pipe = ServingPipeline(eng, backstage="serial", max_queue=1)
    rows = _rows(3)
    pipe.submit(_tick("t0", rows[0]))
    pipe.submit(_tick("t1", rows[1]))  # shed
    pipe.drain()
    eng.flush_metrics()
    snap = telemetry.snapshot()
    assert snap["counters"]["serving.admission.submitted"] == 1
    assert snap["counters"]["serving.admission.shed.queue_full"] == 1
    assert snap["counters"]["serving.pipeline.rounds"] >= 1
    assert "serving.admission.depth" in snap["gauges"]
    # the new admit phase feeds the occupancy split
    assert snap["gauges"].get("serving.occupancy.admit_s", 0) > 0
    pipe.close()


def test_metrics_flush_rides_commit_stage(tmp_path, monkeypatch):
    """Satellite 2: with a pipeline attached, the every-1024-requests
    flush happens on the commit stage, not the admission path."""
    monkeypatch.delenv("DFM_TELEMETRY", raising=False)
    telemetry.enable(sink=str(tmp_path / "t.jsonl"))
    eng = _mk(str(tmp_path / "s"), 2)
    pipe = ServingPipeline(eng, backstage="manual", max_round_lanes=2)
    eng._requests = 1023  # next submission is the 1024th request
    pipe.submit(_tick("t0", _rows(1)[0]))
    assert eng._metrics_due  # parked, NOT flushed on the request path
    pipe.pump()
    assert eng._metrics_due  # front half ran; still parked
    pipe.step_back()          # journal
    assert eng._metrics_due
    pipe.step_back()          # commit drains the deferred flush
    assert not eng._metrics_due
    pipe.close()


def test_queue_full_and_stall_commit_trigger_flight_dumps(
    tmp_path, monkeypatch
):
    """Satellite: both new fault kinds are pre-mortem triggers — a shed
    admission and a stalled commit each leave a flight bundle."""
    d = str(tmp_path / "flight")
    monkeypatch.setenv("DFM_FLIGHT_DIR", d)
    monkeypatch.setenv("DFM_FLIGHT_MIN_INTERVAL_S", "0")
    monkeypatch.delenv("DFM_TELEMETRY", raising=False)
    telemetry.enable(sink=str(tmp_path / "t.jsonl"))
    eng = _mk(str(tmp_path / "s"), 2)
    pipe = ServingPipeline(eng, backstage="serial", max_queue=1)
    rows = _rows(2)
    pipe.submit(_tick("t0", rows[0]))
    pipe.submit(_tick("t1", rows[1]))  # queue_full -> dump
    with faults.inject("stall_commit@1"):
        pipe.drain()                   # stall_commit -> dump
    pipe.close()
    dumps = sorted(glob.glob(os.path.join(d, "flight-*.json")))
    triggers = set()
    import json

    for p in dumps:
        with open(p) as f:
            triggers.add(json.load(f)["trigger"]["trigger"])
    assert "queue_full" in triggers
    assert "stall_commit" in triggers


# ---------------------------------------------------------------------------
# 5. kill matrix: every stage boundary, every i/o site
# ---------------------------------------------------------------------------


def _acked_by_tenant(responses):
    out = {}
    for r in responses:
        if r.ok:
            out[r.tenant] = out.get(r.tenant, 0) + 1
    return out


def _run_killed(store, reqs, hook=None, fault_spec=None):
    """Drive the pipelined workload until done or SimulatedCrash;
    returns per-tenant ACKED tick counts (responses actually polled
    before the crash)."""
    eng = _mk(store, 4)
    pipe = ServingPipeline(
        eng, backstage="serial", max_round_lanes=4, boundary_hook=hook,
    )
    acked = []
    try:
        ctx = faults.inject(fault_spec) if fault_spec else None
        if ctx:
            ctx.__enter__()
        try:
            for r in reqs:
                pipe.submit(r)
                acked.extend(pipe.poll())
            while pipe.depth() or pipe.stats()["inflight"]:
                pipe.pump()
                acked.extend(pipe.poll())
        finally:
            if ctx:
                ctx.__exit__(None, None, None)
    except faults.SimulatedCrash:
        return _acked_by_tenant(acked), True
    finally:
        pipe.close()
    acked.extend(pipe.poll())
    return _acked_by_tenant(acked), False


def _assert_exactly_once(store, acked, tag):
    """Per tenant: acked ≤ recovered ≤ acked + 1; double restart
    bit-identical; nothing quarantined."""
    rec = _engine(store)
    rec2 = _engine(store)
    for tid in ("t0", "t1", "t2", "t3"):
        ten = rec._lookup(tid)
        assert ten is not None, f"{tag}: {tid} lost"
        recovered = int(ten.state.t) - T
        a = acked.get(tid, 0)
        assert a <= recovered <= a + 1, (
            f"{tag}: tenant {tid} acked {a}, recovered {recovered}"
        )
        ten2 = rec2._lookup(tid)
        assert int(ten2.state.t) == int(ten.state.t)
        np.testing.assert_array_equal(
            np.asarray(ten.state.s), np.asarray(ten2.state.s)
        )
    assert not glob.glob(os.path.join(store, "*.corrupt"))


@pytest.mark.chaos_serving
def test_kill_matrix_every_stage_boundary(tmp_path):
    """Acceptance: the PR 13 exactly-once contract holds with the
    pipeline enabled, killed at EVERY stage boundary of every round."""
    reqs = _workload(n_tenants=4, ticks=2, seed=23)
    stages = ("admit", "dispatch", "journal", "commit")
    killed = 0
    for stage in stages:
        for kill_round in (0, 1):
            store = str(
                tmp_path / f"kill_{stage}_{kill_round}"
            )

            def hook(s, rnd, _stage=stage, _kr=kill_round):
                if s == _stage and rnd == _kr:
                    raise faults.SimulatedCrash(
                        f"boundary kill after {s} of round {rnd}"
                    )

            acked, crashed = _run_killed(store, reqs, hook=hook)
            assert crashed, (stage, kill_round)
            killed += 1
            _assert_exactly_once(
                store, acked, f"boundary {stage}/{kill_round}"
            )
    assert killed == len(stages) * 2


@pytest.mark.chaos_serving
def test_kill_matrix_crash_io_sites_pipelined(tmp_path):
    """crash_io@n killed at every store i/o site of the pipelined
    workload (registration sites excluded via the op-counter offset)."""
    reqs = _workload(n_tenants=4, ticks=2, seed=29)
    # measure the registration site count once on a throwaway store
    probe = _mk(str(tmp_path / "probe"), 4)
    reg_ops = probe.store._io_ops
    site, crashes = 0, 0
    while True:
        site += 1
        store = str(tmp_path / f"io{site}")
        acked, crashed = _run_killed(
            store, reqs, fault_spec=f"crash_io@{reg_ops + site}"
        )
        if not crashed:
            break  # site count walked off the end of the workload
        crashes += 1
        _assert_exactly_once(store, acked, f"crash_io site {site}")
    assert crashes >= 4  # the drill covered the round's journal sites


# ---------------------------------------------------------------------------
# 6. tenant-sharded router
# ---------------------------------------------------------------------------


def test_worker_hash_stable_and_partitions_disjoint(tmp_path):
    assert worker_of("alpha", 4) == worker_of("alpha", 4)
    assert 0 <= worker_of("alpha", 4) < 4
    # partition paths are disjoint per worker
    parts = {worker_partition(str(tmp_path), i) for i in range(4)}
    assert len(parts) == 4
    with pytest.raises(ValueError):
        TenantRouter(0)
    with pytest.raises(ValueError):
        TenantRouter(1, backend="carrier_pigeon")


def test_router_inproc_routing_and_flush(tmp_path):
    rt = TenantRouter(2, store_dir=str(tmp_path / "rt"), backend="inproc",
                      engine_kwargs={"max_em_iter": 5,
                                     "retry_policy": _POLICY})
    rt.register_seed("seed", _panel(3))
    ids = [f"c{i}" for i in range(6)]
    for tid in ids:
        rt.register_shared(tid, "seed")
    rng = np.random.default_rng(5)
    # point routing: the owning engine (and only it) holds the tenant
    for tid in ids:
        w = rt.worker_of(tid)
        assert tid in rt._engines[w]._tenants
        assert tid not in rt._engines[1 - w]._tenants
    r = rt.handle(_tick("c0", rng.standard_normal(N)))
    assert r.ok
    rt.submit([_tick(tid, rng.standard_normal(N)) for tid in ids])
    out = rt.flush_all()
    assert len(out) == 6 and all(o.ok for o in out)
    # each worker's store partition holds exactly its own tenants
    for i in range(2):
        stored = set(rt._engines[i].store.list())
        assert stored == {
            t for t in ids + ["seed"] if rt.worker_of(t) == i
        } | {"seed"}
    rt.close()


def test_router_gang_refit(tmp_path):
    rt = TenantRouter(2, store_dir=str(tmp_path / "rt"), backend="inproc",
                      engine_kwargs={"max_em_iter": 4,
                                     "retry_policy": _POLICY})
    for i in range(3):
        rt.register(f"g{i}", _panel(seed=40 + i))
    for i in range(3):
        assert rt.handle({"kind": "refit", "tenant": f"g{i}"}).ok
    summary = rt.flush_refits()
    assert summary["n_requests"] == 3
    assert summary["installed"] == 3 and summary["failed"] == []
    # refits actually installed: queues drained everywhere
    assert all(not e._refit_queue for e in rt._engines)
    rt.close()


def test_router_pipelined_inproc(tmp_path):
    rt = TenantRouter(
        2, store_dir=str(tmp_path / "rt"), backend="inproc",
        pipelined=True,
        pipeline_kwargs={"backstage": "serial", "max_round_lanes": 8},
        engine_kwargs={"max_em_iter": 5, "retry_policy": _POLICY},
    )
    rt.register_seed("seed", _panel(6))
    ids = [f"p{i}" for i in range(6)]
    for tid in ids:
        rt.register_shared(tid, "seed")
    rng = np.random.default_rng(8)
    rt.submit([_tick(tid, rng.standard_normal(N)) for tid in ids])
    out = rt.flush_all()
    assert len(out) == 6 and all(o.ok for o in out)
    stats = rt.stats()
    # every worker that received requests pipelined at least one round
    assert all(
        s["pipeline"]["rounds"] >= 1
        for s in stats if s["pipeline"]["submitted"]
    )
    assert sum(s["pipeline"]["rounds"] for s in stats) >= 1
    rt.close()


@pytest.mark.slow
def test_router_process_backend(tmp_path):
    """OS-process workers: register/tick/flush round-trip the pipe with
    numpy-sanitized responses; a restarted router recovers each
    partition independently."""
    store = str(tmp_path / "rt")
    rt = TenantRouter(
        2, store_dir=store, backend="process", pipelined=True,
        pipeline_kwargs={"backstage": "thread", "max_round_lanes": 64},
    )
    try:
        rt.register_seed("seed", _panel(2))
        ids = [f"c{i}" for i in range(6)]
        for tid in ids:
            rt.register_shared(tid, "seed")
        rng = np.random.default_rng(2)
        resp = rt.handle(_tick("c0", rng.standard_normal(N)))
        assert resp.ok
        assert isinstance(np.asarray(resp.result.s), np.ndarray)
        rt.submit([_tick(tid, rng.standard_normal(N)) for tid in ids])
        out = rt.flush_all()
        assert len(out) == 6 and all(o.ok for o in out)
        stats = rt.stats()
        assert sum(s["resident"] for s in stats) == 8
        assert all("pipeline" in s for s in stats)
    finally:
        rt.close()
    rt2 = TenantRouter(2, store_dir=store, backend="process")
    try:
        rec = rt2.recover(prewarm=8)
        assert sum(r["tenants_on_disk"] for r in rec) == 8
        # c0 ticked twice pre-restart; this tick's result is T+3
        r2 = rt2.handle(_tick("c0", np.zeros(N)))
        assert r2.ok and int(r2.result.t) == T + 3
    finally:
        rt2.close()
