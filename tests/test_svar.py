"""Structural identification: proxy SVAR, sign restrictions, local
projections (models/svar.py) — synthetic ground-truth recovery tests."""

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.svar import (
    SignRestriction,
    local_projection,
    proxy_bootstrap_irfs,
    proxy_impact,
    proxy_irfs,
    sign_restriction_irfs,
)
from dynamic_factor_models_tpu.models.var import estimate_var, impulse_response


def _simulate_svar(T=4000, seed=0):
    """Trivariate SVAR(1) with known impact matrix B0 and an instrument for
    shock 0: z = eps0 + noise."""
    rng = np.random.default_rng(seed)
    B0 = np.array([[1.0, 0.0, 0.0], [0.5, 0.8, 0.0], [-0.4, 0.3, 0.6]])
    A1 = np.array([[0.5, 0.1, 0.0], [0.0, 0.4, 0.1], [0.1, 0.0, 0.3]])
    eps = rng.standard_normal((T, 3))
    y = np.zeros((T, 3))
    for t in range(1, T):
        y[t] = A1 @ y[t - 1] + B0 @ eps[t]
    z = eps[:, 0] + 0.4 * rng.standard_normal(T)
    return y, z, eps, B0, A1


class TestProxySVAR:
    def test_impact_recovers_truth(self):
        y, z, eps, B0, _ = _simulate_svar()
        var = estimate_var(jnp.asarray(y), 1, 0, y.shape[0] - 1)
        pid = proxy_impact(var.resid, jnp.asarray(z), policy=0)
        b = np.asarray(pid.impact)
        if b[0] < 0:
            b = -b
        # one-sd impact column of shock 0 is B0[:, 0]
        assert np.allclose(b, B0[:, 0], atol=0.08)
        # unit normalization: policy entry exactly 1
        assert float(pid.relative[0]) == pytest.approx(1.0)
        assert float(pid.first_stage_f) > 100.0

    def test_impact_masks_missing_rows(self):
        y, z, *_ = _simulate_svar(T=800)
        var = estimate_var(jnp.asarray(y), 1, 0, y.shape[0] - 1)
        z_nan = z.copy()
        z_nan[:150] = np.nan  # instrument starts later than the VAR sample
        pid_full = proxy_impact(var.resid, jnp.asarray(z), 0)
        pid_mask = proxy_impact(var.resid, jnp.asarray(z_nan), 0)
        assert np.isfinite(np.asarray(pid_mask.impact)).all()
        # same identification from the overlapping sample, looser agreement
        assert np.allclose(
            np.asarray(pid_mask.relative), np.asarray(pid_full.relative), atol=0.15
        )

    def test_irfs_match_truth_at_impact(self):
        y, z, _, B0, A1 = _simulate_svar()
        var = estimate_var(jnp.asarray(y), 1, 0, y.shape[0] - 1)
        irf, pid = proxy_irfs(var, jnp.asarray(z), policy=0, horizon=8)
        irf = np.asarray(irf)
        if irf[0, 0] < 0:
            irf = -irf
        assert irf.shape == (3, 8)
        assert np.allclose(irf[:, 0], B0[:, 0], atol=0.08)
        # horizon-1 response: A1 @ B0[:, 0]
        assert np.allclose(irf[:, 1], A1 @ B0[:, 0], atol=0.08)

    def test_bootstrap_brackets_point(self):
        y, z, *_ = _simulate_svar(T=600)
        bs = proxy_bootstrap_irfs(
            jnp.asarray(y), jnp.asarray(z), 1, 0, y.shape[0] - 1,
            policy=0, horizon=8, n_reps=64, seed=1,
        )
        assert bs.draws.shape == (64, 3, 8)
        assert np.isfinite(np.asarray(bs.draws)).all()
        lo, hi = np.asarray(bs.quantiles[0]), np.asarray(bs.quantiles[-1])
        point = np.asarray(bs.point)
        # 5-95% band brackets the point estimate almost everywhere
        frac = np.mean((point >= lo) & (point <= hi))
        assert frac > 0.9

    def test_bootstrap_masks_missing_instrument(self):
        """Replications must mask instrument NaNs like the point estimate,
        not treat them as z=0 observations."""
        y, z, *_ = _simulate_svar(T=600, seed=8)
        z_nan = z.copy()
        z_nan[:200] = np.nan
        bs_nan = proxy_bootstrap_irfs(
            jnp.asarray(y), jnp.asarray(z_nan), 1, 0, y.shape[0] - 1,
            policy=0, horizon=4, n_reps=32, seed=2,
        )
        assert np.isfinite(np.asarray(bs_nan.draws)).all()
        z_zero = np.where(np.isnan(z_nan), 0.0, z_nan)
        bs_zero = proxy_bootstrap_irfs(
            jnp.asarray(y), jnp.asarray(z_zero), 1, 0, y.shape[0] - 1,
            policy=0, horizon=4, n_reps=32, seed=2,
        )
        # zero-filling changes the moments — the draws must differ
        assert not np.allclose(
            np.asarray(bs_nan.draws), np.asarray(bs_zero.draws)
        )


class TestSignRestrictions:
    def test_accepted_draws_satisfy_restrictions(self):
        y, *_ = _simulate_svar(T=1000, seed=2)
        var = estimate_var(jnp.asarray(y), 1, 0, y.shape[0] - 1)
        restr = [
            SignRestriction(variable=0, shock=0, horizon=0, sign=+1),
            SignRestriction(variable=1, shock=0, horizon=0, sign=+1),
        ]
        res = sign_restriction_irfs(var, restr, horizon=8, n_draws=256, seed=0)
        assert 0.0 < res.acceptance_rate < 1.0
        acc = np.asarray(res.draws)[np.asarray(res.accepted)]
        assert (acc[:, 0, 0, 0] > 0).all()
        assert (acc[:, 1, 0, 0] > 0).all()
        # median IRF respects the restrictions too
        med = res.quantiles[len(res.quantile_levels) // 2]
        assert med[0, 0, 0] > 0 and med[1, 0, 0] > 0

    def test_draws_preserve_covariance(self):
        """Every candidate impact B satisfies B B' = seps (rotation property)."""
        y, *_ = _simulate_svar(T=500, seed=3)
        var = estimate_var(jnp.asarray(y), 1, 0, y.shape[0] - 1)
        restr = [SignRestriction(0, 0, 0, +1)]
        res = sign_restriction_irfs(var, restr, horizon=4, n_draws=16, seed=1)
        impacts = np.asarray(res.draws)[:, :, 0, :]  # (n, ns, ns) at h=0
        seps = np.asarray(var.seps)
        for B in impacts:
            assert np.allclose(B @ B.T, seps, atol=1e-8)

    def test_infeasible_restrictions_raise(self):
        y, *_ = _simulate_svar(T=500, seed=4)
        var = estimate_var(jnp.asarray(y), 1, 0, y.shape[0] - 1)
        # contradictory: same IRF entry forced positive and negative
        restr = [SignRestriction(0, 0, 0, +1), SignRestriction(0, 0, 0, -1)]
        with pytest.raises(ValueError, match="no accepted draws"):
            sign_restriction_irfs(var, restr, horizon=4, n_draws=32, seed=0)


class TestLocalProjection:
    def test_recovers_known_dynamic_multiplier(self):
        """y_t = rho y_{t-1} + b s_t + e_t: LP coefficient at h is b rho^h."""
        rng = np.random.default_rng(5)
        T, rho, b = 6000, 0.8, 0.5
        s = rng.standard_normal(T)
        e = 0.3 * rng.standard_normal(T)
        y = np.zeros(T)
        for t in range(1, T):
            y[t] = rho * y[t - 1] + b * s[t] + e[t]
        lp = local_projection(jnp.asarray(y), jnp.asarray(s), max_horizon=6)
        truth = b * rho ** np.arange(7)
        assert np.allclose(np.asarray(lp.irf), truth, atol=0.05)
        assert (np.asarray(lp.se) > 0).all()
        # nobs shrinks by one per horizon (trailing leads drop out)
        nobs = np.asarray(lp.nobs)
        assert (nobs[:-1] - nobs[1:] == 1).all()

    def test_matches_var_irf_on_var_data(self):
        """On VAR(1)-generated data, the LP IRF to the orthogonalized shock
        equals the VAR IRF in population (Jorda 2005 equivalence)."""
        y, _, eps, B0, A1 = _simulate_svar(T=8000, seed=6)
        var = estimate_var(jnp.asarray(y), 1, 0, y.shape[0] - 1)
        virf = np.asarray(impulse_response(var, 0, 6))  # (ns, H) shock 0
        # LP of variable 1 on the Cholesky-orthogonalized first innovation
        shock = eps[:, 0]  # true structural shock (observed in simulation)
        lp = local_projection(
            jnp.asarray(y[:, 1]), jnp.asarray(shock), max_horizon=5,
            controls=jnp.asarray(
                np.column_stack([np.r_[np.nan, y[:-1, 0]],
                                 np.r_[np.nan, y[:-1, 1]],
                                 np.r_[np.nan, y[:-1, 2]]])
            ),
        )
        truth = np.array(
            [(np.linalg.matrix_power(A1, h) @ B0[:, 0])[1] for h in range(6)]
        )
        assert np.allclose(np.asarray(lp.irf), truth, atol=0.06)
        # and the VAR's Cholesky IRF agrees (B0 is lower-triangular, so
        # recursive identification is correct for this DGP)
        scale = B0[0, 0] / virf[0, 0]
        assert np.allclose(virf[1, :6] * scale, truth, atol=0.06)

    def test_handles_missing_values(self):
        rng = np.random.default_rng(7)
        T = 3000
        s = rng.standard_normal(T)
        y = 0.5 * s + 0.2 * rng.standard_normal(T)
        y[rng.random(T) < 0.05] = np.nan
        lp = local_projection(jnp.asarray(y), jnp.asarray(s), max_horizon=3)
        assert np.isfinite(np.asarray(lp.irf)).all()
        assert float(lp.irf[0]) == pytest.approx(0.5, abs=0.05)
