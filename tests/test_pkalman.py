"""Parallel-in-time Kalman (associative scan) vs the sequential reference
implementation, plus the time-block-sharded scan on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from dynamic_factor_models_tpu.models.pkalman import (
    combine_filter,
    filter_elements,
    kalman_filter_associative,
    kalman_smoother_associative,
)
from dynamic_factor_models_tpu.models.ssm import (
    SSMParams,
    _filter_scan,
    _smoother_scan,
    kalman_filter,
    kalman_smoother,
)
from dynamic_factor_models_tpu.ops.masking import fillz, mask_of


def _synthetic(T=64, N=10, r=3, p=2, miss=0.15, seed=0):
    rng = np.random.default_rng(seed)
    A1, A2 = 0.5 * np.eye(r), 0.2 * np.eye(r)
    f = np.zeros((T, r))
    for t in range(p, T):
        f[t] = A1 @ f[t - 1] + A2 @ f[t - 2] + rng.standard_normal(r)
    lam = rng.standard_normal((N, r))
    x = f @ lam.T + 0.7 * rng.standard_normal((T, N))
    x[rng.random((T, N)) < miss] = np.nan
    params = SSMParams(
        lam=jnp.asarray(lam),
        R=0.5 * jnp.ones(N),
        A=jnp.stack([jnp.asarray(A1), jnp.asarray(A2)]),
        Q=jnp.eye(r),
    )
    return params, jnp.asarray(x)


@pytest.fixture(scope="module")
def problem():
    return _synthetic()


def test_filter_parity(problem):
    params, x = problem
    xz, m = fillz(x), mask_of(x)
    seq = _filter_scan(params, xz, m)
    par = kalman_filter_associative(params, xz, m)
    np.testing.assert_allclose(par.loglik, seq.loglik, rtol=1e-10)
    np.testing.assert_allclose(par.means, seq.means, atol=1e-10)
    np.testing.assert_allclose(par.covs, seq.covs, atol=1e-10)
    np.testing.assert_allclose(par.pred_means, seq.pred_means, atol=1e-10)
    np.testing.assert_allclose(par.pred_covs, seq.pred_covs, atol=1e-10)


def test_smoother_parity_including_lag1(problem):
    params, x = problem
    xz, m = fillz(x), mask_of(x)
    filt = _filter_scan(params, xz, m)
    sm_means, sm_covs, lag1_seq = _smoother_scan(params, filt)
    pm, pc, ll, lag1_par = kalman_smoother_associative(params, xz, m)
    np.testing.assert_allclose(pm, sm_means, atol=1e-10)
    np.testing.assert_allclose(pc, sm_covs, atol=1e-10)
    np.testing.assert_allclose(lag1_par, lag1_seq, atol=1e-10)
    np.testing.assert_allclose(ll, filt.loglik, rtol=1e-10)


def test_public_method_kwarg(problem):
    params, x = problem
    a = kalman_filter(params, x, method="associative")
    s = kalman_filter(params, x, method="sequential")
    np.testing.assert_allclose(a.loglik, s.loglik, rtol=1e-10)
    ma, _, lla = kalman_smoother(params, x, method="associative")
    ms, _, lls = kalman_smoother(params, x, method="sequential")
    np.testing.assert_allclose(ma, ms, atol=1e-10)
    np.testing.assert_allclose(lla, lls, rtol=1e-10)


def test_no_missing_and_heavy_missing():
    for miss in (0.0, 0.6):
        params, x = _synthetic(miss=miss, seed=1)
        xz, m = fillz(x), mask_of(x)
        seq = _filter_scan(params, xz, m)
        par = kalman_filter_associative(params, xz, m)
        np.testing.assert_allclose(par.means, seq.means, atol=1e-9)
        np.testing.assert_allclose(par.loglik, seq.loglik, rtol=1e-9)


@pytest.mark.slow
def test_sharded_scan_matches_associative(problem):
    params, x = problem
    xz, m = fillz(x), mask_of(x)
    from dynamic_factor_models_tpu.parallel.timescan import sharded_scan

    devs = jax.devices()
    mesh = Mesh(np.array(devs[:8]), ("time",))
    elems = filter_elements(params, xz, m)
    ref = jax.lax.associative_scan(combine_filter, elems)
    shd = sharded_scan(combine_filter, elems, mesh)
    np.testing.assert_allclose(np.asarray(shd.b), np.asarray(ref.b), atol=1e-10)
    np.testing.assert_allclose(np.asarray(shd.C), np.asarray(ref.C), atol=1e-10)


@pytest.mark.slow
def test_sequence_parallel_smoother_on_mesh(problem):
    """Full smoother with time-block sharding across 8 devices — the
    sequence-parallel path end to end."""
    params, x = problem
    xz, m = fillz(x), mask_of(x)
    from dynamic_factor_models_tpu.parallel.timescan import sharded_scan

    mesh = Mesh(np.array(jax.devices()[:8]), ("time",))
    scan = lambda f, e: sharded_scan(f, e, mesh)
    pm, pc, ll, lag1 = kalman_smoother_associative(params, xz, m, scan=scan)
    filt = _filter_scan(params, xz, m)
    sm_means, sm_covs, lag1_seq = _smoother_scan(params, filt)
    np.testing.assert_allclose(np.asarray(pm), np.asarray(sm_means), atol=1e-9)
    np.testing.assert_allclose(np.asarray(ll), np.asarray(filt.loglik), rtol=1e-10)
    np.testing.assert_allclose(np.asarray(lag1), np.asarray(lag1_seq), atol=1e-9)


def test_sharded_scan_pads_ragged_blocks(problem):
    """T % n_dev != 0 no longer rejects: the element pytree is padded at
    the end with repeats of the last element (causally inert for an
    inclusive forward scan) and the padded outputs are sliced off."""
    params, x = problem
    from dynamic_factor_models_tpu.parallel.timescan import sharded_scan

    mesh = Mesh(np.array(jax.devices()[:8]), ("time",))
    elems = filter_elements(params, fillz(x)[:63], mask_of(x)[:63])
    ref = jax.lax.associative_scan(combine_filter, elems)
    shd = sharded_scan(combine_filter, elems, mesh)
    np.testing.assert_allclose(np.asarray(shd.b), np.asarray(ref.b), atol=1e-12)
    np.testing.assert_allclose(np.asarray(shd.C), np.asarray(ref.C), atol=1e-12)
