"""Replication-driver smoke tests + checkpoint round-trip."""

import os

import numpy as np
import pytest

from dynamic_factor_models_tpu.replication import stock_watson as sw
from dynamic_factor_models_tpu.utils.checkpoint import load_pytree, save_pytree


def test_figure1_and_5_shapes(dataset_real):
    f1 = sw.figure1(dataset_real)
    assert set(f1["series"]) == {"GDPC96", "INDPRO", "PAYEMS", "A0M057"}
    for v in f1["series"].values():
        assert v["actual"].shape == (224,) and v["common"].shape == (224,)
        # common component tracks the actual series
        m = np.isfinite(v["actual"]) & np.isfinite(v["common"])
        assert np.corrcoef(v["actual"][m], v["common"][m])[0, 1] > 0.5

    f5 = sw.figure5(dataset_real)
    m = np.isfinite(f5["full"]) & np.isfinite(f5["pre"])
    # split-sample estimates of the same factor agree in-sample
    assert abs(np.corrcoef(f5["full"][m], f5["pre"][m])[0, 1]) > 0.9


def test_figure2_filters():
    f2 = sw.figure2()
    for k in ("biweight", "ma40", "bandpass"):
        w = f2["weights"][k]
        assert w.shape == (201,)
        np.testing.assert_allclose(w.sum(), 1.0, atol=1e-10)
        g = f2["gains"][k]
        assert abs(g[0] - 1.0) < 1e-6  # unit gain at frequency zero
        assert g[-1] < 0.2  # low-pass behavior


def test_figure6_monotone_cumulative_r2(dataset_all):
    f6 = sw.figure6(dataset_all, max_r=5)
    tr = f6["all"]
    assert np.all(np.diff(tr[np.isfinite(tr)]) > 0)
    np.testing.assert_allclose(tr[0], 0.215, atol=1e-3)  # cell 37 r=1


def test_table3_r2_increasing(dataset_all):
    t3 = sw.table3(dataset_all, nfac_max=3)
    assert t3.shape == (207, 3)
    fin = np.isfinite(t3).all(axis=1)
    # factor spaces are re-estimated per r (not nested), so per-series R^2
    # can dip slightly; but the average must rise and large dips are bugs
    assert (np.diff(t3[fin].mean(axis=0)) > 0).all()
    assert (np.diff(t3[fin], axis=1) > -0.05).mean() > 0.95


def test_checkpoint_roundtrip(tmp_path, dataset_real):
    from dynamic_factor_models_tpu.models.dfm import DFMConfig, estimate_dfm

    res = estimate_dfm(dataset_real.bpdata, dataset_real.inclcode, 2, 223, DFMConfig())
    p = str(tmp_path / "dfm.npz")
    save_pytree(p, res)
    res2 = load_pytree(p, res)
    np.testing.assert_array_equal(np.asarray(res.factor), np.asarray(res2.factor))
    np.testing.assert_array_equal(np.asarray(res.lam), np.asarray(res2.lam))
    np.testing.assert_array_equal(np.asarray(res.var.M), np.asarray(res2.var.M))


def test_checkpoint_rejects_mismatched_template(tmp_path):
    save_pytree(str(tmp_path / "x.npz"), {"a": np.ones(3)})
    with pytest.raises(ValueError, match="leaves"):
        load_pytree(str(tmp_path / "x.npz"), {"a": np.ones(3), "b": np.ones(2)})


def test_hp_trend_weight_matches_reference_file():
    # computed HP smoother weights vs the data file the reference ships
    # (6 printed decimals => tolerance 5e-7); vendored copy in repo data/
    import os

    from dynamic_factor_models_tpu.ops.filters import hp_trend_weight

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "data", "hpfilter_trend.asc")
    if not os.path.exists(path):
        path = "/root/reference/data/hpfilter_trend.asc"
    if not os.path.exists(path):
        pytest.skip("HP weight file not present")
    ref = np.loadtxt(path)
    w = np.asarray(hp_trend_weight(100))
    assert w.shape == ref.shape
    np.testing.assert_allclose(w, ref, atol=5.1e-7)
    # and the analytic properties: symmetric, sums to 1
    np.testing.assert_allclose(w, w[::-1], rtol=1e-10)
    np.testing.assert_allclose(w.sum(), 1.0, rtol=1e-10)


def test_plotting_line_panel_and_figure2_render(tmp_path):
    matplotlib = pytest.importorskip("matplotlib")
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    from dynamic_factor_models_tpu.replication.plotting import SURFACE, line_panel
    from dynamic_factor_models_tpu.replication.stock_watson import figure2

    f2 = figure2()
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(8, 3))
    line_panel(ax1, f2["laglead"], f2["weights"], "weights")
    line_panel(ax2, f2["frequencies"], f2["gains"], "gains")
    out = tmp_path / "fig2.png"
    fig.savefig(out, facecolor=SURFACE)
    plt.close(fig)
    assert out.stat().st_size > 10_000
    # legend present for multi-series panels (accessibility rule)
    assert ax1.get_legend() is not None and ax2.get_legend() is not None


@pytest.mark.slow
def test_run_all_fast_bundle():
    """The full replication driver wiring end-to-end (fast mode: trimmed
    sweeps). Shape/content sanity of every figure/table in the bundle."""
    from dynamic_factor_models_tpu.replication.stock_watson import run_all

    out = run_all(fast=True)
    assert set(out) == {
        "figure1", "figure2", "figure4", "figure5", "figure6", "figure7",
        "table2", "table3", "table4", "table5",
    }
    assert set(out["figure1"]["series"]) == {"GDPC96", "INDPRO", "PAYEMS", "A0M057"}
    assert out["table2"]["A"]["trace_r2"].shape == (6,)
    assert np.isfinite(out["table2"]["B"]["bn_icp"]).all()
    assert out["table3"].shape[1] == 4
    assert out["figure6"]["all"].shape == (10,)
    assert 4 in out["table4"]
    for key in ("A", "B", "O"):
        assert np.isfinite(out["table5"][key]["residual_cca"]).all()
    assert np.isfinite(out["figure7"]["common_component"]).sum() > 100


def test_cli_driver_help_and_json():
    """CLI module parses args and its JSON encoder handles the bundle types."""
    import subprocess
    import sys

    from dynamic_factor_models_tpu.replication.__main__ import _to_jsonable

    out = subprocess.run(
        [sys.executable, "-m", "dynamic_factor_models_tpu.replication", "--help"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0 and "--full" in out.stdout
    enc = _to_jsonable({"a": np.array([1.0, np.nan]), "b": (np.int64(2), "s")})
    assert enc == {"a": [1.0, None], "b": [2, "s"]}


def _load_bench_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py"),
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def test_bench_fragment_parsing_and_flops_models():
    """Orchestrator plumbing: the JSON-fragment scraper tolerates noise
    around the line, and the FLOPs models scale with their leading terms."""
    bench = _load_bench_module()

    class FakeProc:
        stdout = 'compiling...\n{"metric": "x", "value": 1.5}\ntrailing\n'

    frag = bench._parse_fragment(FakeProc())
    assert frag == {"metric": "x", "value": 1.5}

    class Empty:
        stdout = "no json here\n"

    assert bench._parse_fragment(Empty()) is None

    # leading-order scaling: 2x series at fixed (T, r) ~ doubles the work
    assert 1.9 < bench.als_iter_flops(2048, 8192, 8) / bench.als_iter_flops(
        2048, 4096, 8
    ) < 2.1
    assert 1.5 < bench.em_iter_flops(2048, 8192, 8, 1) / bench.em_iter_flops(
        2048, 4096, 8, 1
    ) < 2.1


def test_bench_run_child_timeout_returns_failure(monkeypatch):
    """A wedging --run-main child (TimeoutExpired) must come back as a
    failed-proc object, not an uncaught exception, so the orchestrator can
    keep the already-computed CPU fragment."""
    bench = _load_bench_module()
    pr = bench._run_child(
        ["--run-parity-programs"], timeout_s=0.0001
    )  # any child: killed before it can start
    assert pr.returncode != 0
    assert bench._parse_fragment(pr) is None


@pytest.mark.slow
def test_render_extras_writes_capability_panels(tmp_path):
    """The beyond-reference panels (SV volatility, posterior IRF fan, TVP
    loadings, series-space IRF band, coherence) render to non-trivial PNGs
    with tiny chains."""
    from dynamic_factor_models_tpu.replication.plotting import render_extras

    written = render_extras(str(tmp_path), n_keep=8, n_burn=8, n_chains=2, ms_steps=80)
    names = sorted(os.path.basename(p) for p in written)
    assert names == [
        "extra_coherence.png",
        "extra_forecast_fan.png",
        "extra_posterior_irf.png",
        "extra_recession_prob.png",
        "extra_series_irf_band.png",
        "extra_sv_volatility.png",
        "extra_tvp_loadings.png",
    ]
    assert all(os.path.getsize(p) > 10_000 for p in written)


def test_checkpoint_roundtrip_new_result_types(tmp_path):
    """Every major round-3 result type survives the pytree npz round-trip
    (fitted-model persistence, SURVEY.md section 5.4)."""
    import jax.numpy as jnp

    from dynamic_factor_models_tpu.models.favar import (
        ForecastFan,
        bootstrap_forecast_fan,
    )
    from dynamic_factor_models_tpu.models.multilevel import estimate_multilevel_dfm

    rng = np.random.default_rng(0)
    y = np.zeros((120, 2))
    for t in range(1, 120):
        y[t] = 0.5 * y[t - 1] + rng.standard_normal(2)
    fan = bootstrap_forecast_fan(jnp.asarray(y), 1, 0, 119, horizon=4, n_reps=16)
    p1 = str(tmp_path / "fan.npz")
    save_pytree(p1, fan)
    fan2 = load_pytree(p1, fan)
    np.testing.assert_array_equal(np.asarray(fan.quantiles), np.asarray(fan2.quantiles))

    x = np.hstack([y + rng.standard_normal((120, 2)), y @ rng.standard_normal((2, 2))])
    ml = estimate_multilevel_dfm(x, [np.arange(2), np.arange(2, 4)], 1, 1)
    p2 = str(tmp_path / "ml.npz")
    save_pytree(p2, ml)
    ml2 = load_pytree(p2, ml)
    np.testing.assert_array_equal(
        np.asarray(ml.global_factors), np.asarray(ml2.global_factors)
    )
    np.testing.assert_array_equal(np.asarray(ml.stds), np.asarray(ml2.stds))
    for a, b in zip(ml.block_factors, ml2.block_factors):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert ml2.variance_decomposition.keys() == ml.variance_decomposition.keys()

    # round-4 MS-DFM results persist too (recession-dating deliverable)
    from dynamic_factor_models_tpu.models.msdfm import fit_ms_dfm

    ms = fit_ms_dfm(x, n_steps=40, n_restarts=2)
    p3 = str(tmp_path / "ms.npz")
    save_pytree(p3, ms)
    ms2 = load_pytree(p3, ms)
    np.testing.assert_array_equal(
        np.asarray(ms.smoothed_probs), np.asarray(ms2.smoothed_probs)
    )
    np.testing.assert_array_equal(
        np.asarray(ms.params.mu), np.asarray(ms2.params.mu)
    )
