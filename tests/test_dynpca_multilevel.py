"""Dynamic (spectral) PCA and two-level DFM tests on synthetic data with
known structure."""

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.dynpca import (
    dynamic_eigenvalue_shares,
    dynamic_pca,
    forecast_common_component,
    one_sided_common_component,
    spectral_density,
)
from dynamic_factor_models_tpu.models.multilevel import estimate_multilevel_dfm
from dynamic_factor_models_tpu.ops.cca import canonical_correlations


@pytest.fixture(scope="module")
def gdfm_panel():
    rng = np.random.default_rng(2)
    T, N = 400, 40
    u = rng.standard_normal(T + 2)
    chi = np.zeros((T, N))
    for i in range(N):
        b = rng.standard_normal(3)
        chi[:, i] = b[0] * u[2:] + b[1] * u[1:-1] + b[2] * u[:-2]
    x = chi + 0.8 * rng.standard_normal((T, N))
    return x, chi


def test_dynamic_pca_recovers_common_component(gdfm_panel):
    x, chi = gdfm_panel
    res = dynamic_pca(x, q=1, M=24)
    chi_hat = np.asarray(res.common_component)
    cors = [
        abs(np.corrcoef(chi_hat[30:-30, i], chi[30:-30, i])[0, 1])
        for i in range(x.shape[1])
    ]
    assert np.mean(cors) > 0.95
    # one dynamic factor dominates at every frequency
    ev = np.asarray(res.eigenvalues)
    assert (ev[:, 0] / ev[:, 1]).min() > 5
    assert 0.5 < float(res.variance_share) < 1.0


def test_dynamic_eigenvalue_shares_monotone(gdfm_panel):
    x, _ = gdfm_panel
    res = dynamic_pca(x, q=1, M=16)
    shares = dynamic_eigenvalue_shares(res)
    assert np.all(np.diff(shares) >= -1e-12)
    assert shares[0] > 0.5 and abs(shares[-1] - 1.0) < 1e-8


def test_spectral_density_hermitian_psd(gdfm_panel):
    x, _ = gdfm_panel
    freqs, spec = spectral_density(x[:, :10], M=12)
    s = np.asarray(spec)
    np.testing.assert_allclose(s, np.conj(np.transpose(s, (0, 2, 1))), atol=1e-10)
    ev = np.linalg.eigvalsh(s)
    assert ev.min() > -1e-8


def test_dynamic_pca_white_noise_flat_spectrum():
    rng = np.random.default_rng(7)
    x = rng.standard_normal((500, 12))
    res = dynamic_pca(x, q=1, M=12)
    # no common structure: top eigenvalue share stays near 1/N, far below 0.5
    shares = dynamic_eigenvalue_shares(res)
    assert shares[0] < 0.4


@pytest.fixture(scope="module")
def two_level_panel():
    rng = np.random.default_rng(3)
    T, n_blocks, nb, rg, rb = 300, 4, 15, 2, 1
    N = n_blocks * nb
    F = rng.standard_normal((T, rg))
    G = [rng.standard_normal((T, rb)) for _ in range(n_blocks)]
    x = np.zeros((T, N))
    for c in range(n_blocks):
        Lg = rng.standard_normal((nb, rg))
        Lb = 1.5 * rng.standard_normal((nb, rb))
        x[:, c * nb : (c + 1) * nb] = (
            F @ Lg.T + G[c] @ Lb.T + 0.5 * rng.standard_normal((T, nb))
        )
    x[rng.random((T, N)) < 0.05] = np.nan
    blocks = [np.arange(c * nb, (c + 1) * nb) for c in range(n_blocks)]
    return x, F, G, blocks


def test_multilevel_recovers_both_levels(two_level_panel):
    x, F, G, blocks = two_level_panel
    res = estimate_multilevel_dfm(x, blocks, 2, 1)
    cc = np.asarray(canonical_correlations(res.global_factors, jnp.asarray(F)))
    assert cc.min() > 0.98
    for c, Gc in enumerate(G):
        corr = np.corrcoef(np.asarray(res.block_factors[c][:, 0]), Gc[:, 0])[0, 1]
        assert abs(corr) > 0.9
    vd = res.variance_decomposition
    assert abs(vd["global"] + vd["block"] + vd["idiosyncratic"] - 1.0) < 0.05
    assert vd["idiosyncratic"] < 0.25


def test_multilevel_rejects_overlapping_blocks(two_level_panel):
    x, _, _, _ = two_level_panel
    with pytest.raises(ValueError, match="disjoint"):
        estimate_multilevel_dfm(x, [np.arange(0, 10), np.arange(5, 15)], 1, 1)


def test_one_sided_common_component_recovers_dgp(rng):
    # dynamic one-factor DGP: x_it = a_i f_t + b_i f_{t-1} + xi_it
    T, N = 400, 40
    f = np.zeros(T)
    for t in range(1, T):
        f[t] = 0.7 * f[t - 1] + rng.standard_normal()
    a, b = rng.standard_normal(N), rng.standard_normal(N)
    chi_true = np.outer(f, a) + np.outer(np.roll(f, 1), b)
    chi_true[0] = np.outer(f, a)[0]
    x = chi_true + 0.6 * rng.standard_normal((T, N))

    chi, W, proj, _ = one_sided_common_component(x, q=1, r=2, M=24)
    chi = np.asarray(chi)
    assert chi.shape == (T, N) and W.shape == (N, 2)
    assert np.isfinite(chi).all()
    # the causal estimate tracks the true common component (both in the
    # standardized units the estimator works in)
    chi_std_true = (chi_true - chi_true.mean(0)) / x.std(0)
    corr = np.corrcoef(chi[24:].ravel(), chi_std_true[24:].ravel())[0, 1]
    assert corr > 0.8, f"one-sided common component weak: corr={corr}"
    # causality, exactly: chi must equal the contemporaneous linear map
    # proj (W' xz_t) of the standardized panel — row t never reads other
    # rows, so any future-data leak (e.g. a two-sided filter sneaking in)
    # breaks this equality
    n = (~np.isnan(x)).sum(0)
    std = x.std(0, ddof=1) * np.sqrt((n - 1) / n)
    xz = (x - x.mean(0)) / std
    np.testing.assert_allclose(
        chi, xz @ np.asarray(W) @ np.asarray(proj).T, atol=1e-10
    )


@pytest.mark.slow
def test_multilevel_real_panel_category_blocks(dataset_all):
    """Two-level DFM on the REAL Stock-Watson panel with category blocks
    (floor(catcode) groups play the role of Barigozzi's countries): the
    global+block decomposition must fit better than global-only, and the
    variance decomposition must be sane."""
    from dynamic_factor_models_tpu.models.multilevel import estimate_multilevel_dfm

    ds = dataset_all
    incl = np.asarray(ds.inclcode) == 1
    data = np.asarray(ds.bpdata)[:, incl]
    cats = np.floor(np.asarray(ds.bpcatcode)[incl]).astype(int)
    blocks = [np.nonzero(cats == c)[0] for c in np.unique(cats)]
    blocks = [b for b in blocks if b.size >= 8]
    used = np.concatenate(blocks)
    data = data[:, used]
    # reindex blocks into the compacted panel
    offs = np.cumsum([0] + [b.size for b in blocks[:-1]])
    blocks = [np.arange(o, o + b.size) for o, b in zip(offs, blocks)]

    res = estimate_multilevel_dfm(
        data, blocks, r_global=2, r_block=1, initperiod=2, lastperiod=223,
        tol=1e-6, max_outer=50,
    )
    vd = res.variance_decomposition
    assert 0.15 < vd["global"] < 0.6
    # block structure carries real explanatory power on the real panel
    assert 0.03 < vd["block"] < 0.5
    assert vd["idiosyncratic"] < 0.75
    # shares are computed from non-orthogonalized components, so they sum
    # to ~1 with overlap slack (same convention as the synthetic test)
    assert abs(vd["global"] + vd["block"] + vd["idiosyncratic"] - 1.0) < 0.15


@pytest.mark.slow
class TestCoherence:
    def test_coherent_and_independent_pairs(self):
        from dynamic_factor_models_tpu.models.dynpca import coherence

        rng = np.random.default_rng(0)
        T = 2000
        f = np.zeros(T)
        for t in range(1, T):
            f[t] = 0.9 * f[t - 1] + rng.standard_normal()
        x = np.column_stack([
            f + 0.3 * rng.standard_normal(T),
            np.r_[np.zeros(2), f[:-2]] + 0.3 * rng.standard_normal(T),
            rng.standard_normal(T),
        ])
        freqs, coh2, phase = coherence(jnp.asarray(x), M=40)
        freqs, coh2 = np.asarray(freqs), np.asarray(coh2)
        assert ((coh2 >= 0) & (coh2 <= 1)).all()
        low = freqs <= 0.5
        # series 0 and 1 share the slow factor; series 2 is independent
        assert coh2[low, 0, 1].mean() > 0.8
        assert coh2[low, 0, 2].mean() < 0.2
        # the 2-period lag shows as a positive low-frequency phase slope
        slope = (np.asarray(phase)[1:6, 0, 1] / freqs[1:6]).mean()
        assert 1.0 < slope < 3.0
        # diagonal coherence is exactly 1
        assert np.allclose(coh2[:, 0, 0], 1.0, atol=1e-8)


class TestMultilevelSeriesIRFs:
    """The Barigozzi-Conti-Luciani asymmetry exercise: per-block series
    bands to one common shock (models/multilevel.multilevel_series_irfs)."""

    @staticmethod
    def _panel(g_scale_a, g_scale_b, seed=0, T=300, nb=20):
        rng = np.random.default_rng(seed)

        def ar1():
            u = rng.standard_normal(T) * np.sqrt(1 - 0.7**2)
            f = np.zeros(T)
            for t in range(1, T):
                f[t] = 0.7 * f[t - 1] + u[t]
            return f

        F, Ga, Gb = ar1(), ar1(), ar1()
        La = g_scale_a * (0.5 + np.abs(rng.standard_normal(nb)))
        Lb_ = g_scale_b * (0.5 + np.abs(rng.standard_normal(nb)))
        x = np.zeros((T, 2 * nb))
        x[:, :nb] = np.outer(F, La) + np.outer(Ga, rng.standard_normal(nb))
        x[:, nb:] = np.outer(F, Lb_) + np.outer(Gb, rng.standard_normal(nb))
        x += 0.5 * rng.standard_normal((T, 2 * nb))
        return x, [np.arange(nb), np.arange(nb, 2 * nb)]

    def test_bands_and_asymmetry_ordering(self):
        from dynamic_factor_models_tpu.models.multilevel import (
            multilevel_series_irfs,
        )

        x, blocks = self._panel(g_scale_a=1.5, g_scale_b=0.3)
        res = estimate_multilevel_dfm(x, blocks, 1, 1)
        out = multilevel_series_irfs(res, horizon=8, nlag=2, n_reps=100)
        assert out.r_global == 1 and len(out.series) == 2
        nb = len(blocks[0])
        for s, bs in zip(out.series, out.factor_boots):
            assert s.point.shape == (nb, 8, 2)  # joint [F, G_b] system
            assert s.quantiles.shape == (5, nb, 8, 2)
            assert np.isfinite(np.asarray(s.quantiles)).all()
            assert bs.point.shape == (2, 8, 2)
            # unit-effect normalization: every draw's impact of F on the
            # global shock is exactly 1, so blocks are comparable
            np.testing.assert_allclose(np.asarray(bs.point)[0, 0, 0], 1.0)
            np.testing.assert_allclose(
                np.asarray(bs.draws)[:, 0, 0, 0], 1.0, atol=1e-12
            )
        # block A loads ~5x harder on the global factor: its cumulative
        # absolute response to the common shock (shock 0) must dominate
        resp = [
            np.abs(np.asarray(s.point)[:, :, 0]).sum(axis=1).mean()
            for s in out.series
        ]
        assert resp[0] > 2.0 * resp[1], f"asymmetry not detected: {resp}"

    def test_symmetric_blocks_respond_alike(self):
        from dynamic_factor_models_tpu.models.multilevel import (
            multilevel_series_irfs,
        )

        x, blocks = self._panel(g_scale_a=1.0, g_scale_b=1.0, seed=1)
        res = estimate_multilevel_dfm(x, blocks, 1, 1)
        out = multilevel_series_irfs(res, horizon=8, nlag=2, n_reps=100)
        resp = [
            np.abs(np.asarray(s.point)[:, :, 0]).sum(axis=1).mean()
            for s in out.series
        ]
        assert 0.6 < resp[0] / resp[1] < 1.6, f"spurious asymmetry: {resp}"


def test_forecast_common_component_fhlr(rng):
    """FHLR (2005) h-step common-component forecast: h=0 reduces exactly to
    the one-sided estimator; at h=1,2 a persistent factor stays predictable
    and the forecast beats the unconditional zero forecast in MSE."""
    T, N = 500, 30
    f = np.zeros(T)
    for t in range(1, T):
        f[t] = 0.8 * f[t - 1] + rng.standard_normal() * 0.6
    b = rng.standard_normal(N)
    chi_true = np.outer(f, b)
    x = chi_true + 0.6 * rng.standard_normal((T, N))

    chi0, W, proj0, _ = one_sided_common_component(x, q=1, r=1, M=24)
    chi_h0, proj_h0, _ = forecast_common_component(x, q=1, r=1, h=0, M=24)
    np.testing.assert_allclose(np.asarray(chi_h0), np.asarray(chi0), atol=1e-8)

    std = x.std(0, ddof=1) * np.sqrt((T - 1) / T)
    chi_std = (chi_true - chi_true.mean(0)) / std
    for h in (1, 2):
        chi_f = np.asarray(forecast_common_component(x, q=1, r=1, h=h, M=24)[0])
        pred, real = chi_f[24:-h], chi_std[24 + h :]
        corr = np.corrcoef(pred.ravel(), real.ravel())[0, 1]
        assert corr > 0.55, f"h={h}: corr {corr:.3f}"
        mse = ((pred - real) ** 2).mean()
        assert mse < (real**2).mean(), f"h={h}: no gain over zero forecast"

    with pytest.raises(ValueError, match="h="):
        forecast_common_component(x, q=1, r=1, h=99, M=24)
