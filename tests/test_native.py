"""Native C++ ingest kernel vs the NumPy reference path."""

import contextlib

import numpy as np
import pytest

from dynamic_factor_models_tpu.io import ingest
from dynamic_factor_models_tpu.io import native as native_mod
from dynamic_factor_models_tpu.io.native import biweight_trend_native


@contextlib.contextmanager
def _native_disabled():
    """Force ingest._biweight_trend onto its NumPy fallback path."""
    lib, tried = native_mod._LIB, native_mod._TRIED
    native_mod._LIB, native_mod._TRIED = None, True
    try:
        yield
    finally:
        native_mod._LIB, native_mod._TRIED = lib, tried


@pytest.fixture(scope="module")
def lib_available():
    out = biweight_trend_native(np.zeros((4, 2)), 2.0)
    if out is None:
        pytest.skip("g++ unavailable; native path disabled")
    return True


@pytest.mark.parametrize("T,ns,bw", [(224, 33, 100.0), (50, 7, 8.0), (300, 5, 299.0)])
def test_native_matches_numpy(lib_available, rng, T, ns, bw):
    # compare the two REAL production paths, not a copy of either
    x = rng.standard_normal((T, ns))
    x[rng.random((T, ns)) < 0.1] = np.nan
    got = biweight_trend_native(x, bw)
    with _native_disabled():
        want = ingest._biweight_trend(x, bw)
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-12, equal_nan=True)


def test_ingest_uses_native_transparently(rng):
    # same _biweight_trend output whether or not the native path engages
    x = rng.standard_normal((120, 9))
    x[rng.random((120, 9)) < 0.05] = np.nan
    with_native = ingest._biweight_trend(x, 50.0)
    with _native_disabled():
        without = ingest._biweight_trend(x, 50.0)
    np.testing.assert_allclose(with_native, without, rtol=1e-12, equal_nan=True)
