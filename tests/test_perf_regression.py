"""Round-over-round CPU perf regression lane (round-4 verdict item 7).

Wall-clocks the hot per-iteration programs — collapsed EM step (large panel
and reference scale), the ALS core, and the mixed-frequency EM step — against
budgets ~1.6x the typical quiet in-environment measurement.  That margin
passes ordinary machine noise (observed quiet spread ~±25%) while a
deliberate 2x algorithmic slowdown of any step fails the lane; a regression
therefore surfaces in-round, not only at bench time.

Budgets are in milliseconds of min-of-7 steady-state wall clock, first call
(compile) excluded, measured IN the test environment (conftest enables x64
and the 8-virtual-device CPU platform, which splits the XLA threadpool and
runs these ~3x slower than a plain-platform process — calibrate here, not
in a standalone script).  If hardware changes materially, recalibrate by
running this file and setting budget ~1.6x the typical quiet measurement.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

# round-4 quiet in-environment typical values: 65 / 330 / 20 / 68 ms
BUDGET_EM_LARGE_MS = 110.0
BUDGET_ALS_LARGE_MS = 550.0
BUDGET_EM_REF_MS = 35.0
BUDGET_EM_MF_MS = 110.0


def _min_wall(fn, n=7):
    out = fn()
    jax.block_until_ready(out)  # compile outside the clock
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return min(ts) * 1000.0


def _panel(T, N, missing, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((T, N)).astype(np.float32)
    m = rng.random((T, N)) > missing
    return jnp.asarray(np.where(m, x, 0.0).astype(np.float32)), jnp.asarray(m)


def _ssm_params(N, r, p, dtype=jnp.float32):
    from dynamic_factor_models_tpu.models.ssm import SSMParams

    A = jnp.concatenate(
        [0.5 * jnp.eye(r, dtype=dtype)[None], jnp.zeros((p - 1, r, r), dtype)]
    )
    return SSMParams(
        lam=jnp.zeros((N, r), dtype).at[:, 0].set(1.0),
        R=jnp.ones(N, dtype),
        A=A,
        Q=jnp.eye(r, dtype=dtype),
    )


def test_em_step_large_panel_budget():
    from dynamic_factor_models_tpu.models.ssm import (
        compute_panel_stats,
        em_step_stats,
    )

    xz, m = _panel(1024, 2048, 0.2)
    params = _ssm_params(2048, 8, 1)
    stats = compute_panel_stats(xz, m)
    ms = _min_wall(lambda: em_step_stats(params, xz, m, stats))
    assert ms < BUDGET_EM_LARGE_MS, (
        f"collapsed EM step regressed: {ms:.1f} ms > {BUDGET_EM_LARGE_MS} ms "
        f"budget at (T,N,r)=(1024,2048,8)"
    )


def test_als_core_large_panel_budget():
    from dynamic_factor_models_tpu.models.dfm import _als_core

    xz, m = _panel(1024, 2048, 0.2)
    rng = np.random.default_rng(1)
    f0 = jnp.asarray(rng.standard_normal((1024, 8)).astype(np.float32))
    lam_ok = jnp.ones(2048, bool)
    mf = m.astype(xz.dtype)
    ms = _min_wall(
        lambda: _als_core(xz, mf, lam_ok, f0, jnp.float32(0.0), 8, 4)[0]
    )
    assert ms < BUDGET_ALS_LARGE_MS, (
        f"ALS core regressed: {ms:.1f} ms > {BUDGET_ALS_LARGE_MS} ms budget "
        f"for 4 iterations at (T,N,r)=(1024,2048,8)"
    )


def test_em_step_reference_scale_budget():
    from dynamic_factor_models_tpu.models.ssm import (
        compute_panel_stats,
        em_step_stats,
    )

    xz, m = _panel(224, 139, 0.1)
    params = _ssm_params(139, 4, 4)
    stats = compute_panel_stats(xz, m)
    ms = _min_wall(lambda: em_step_stats(params, xz, m, stats))
    assert ms < BUDGET_EM_REF_MS, (
        f"reference-scale EM step regressed: {ms:.1f} ms > "
        f"{BUDGET_EM_REF_MS} ms budget at (T,N,r,p)=(224,139,4,4)"
    )


def test_em_step_mixed_freq_budget():
    from dynamic_factor_models_tpu.models.mixed_freq import (
        MixedFreqParams,
        em_step_mf_stats,
    )
    from dynamic_factor_models_tpu.models.ssm import compute_panel_stats

    T, N, r, p = 672, 139, 4, 5
    xz, m = _panel(T, N, 0.15, seed=2)
    # quarterly tail observed at quarter ends only
    mask = np.array(m)  # writable copy (np.asarray of a jax array is RO)
    mask[np.arange(T) % 3 != 2, N - 40 :] = False
    m = jnp.asarray(mask)
    xz = jnp.where(m, xz, 0.0)
    agg = np.zeros((N, 5), np.float32)
    agg[: N - 40, 0] = 1.0
    agg[N - 40 :] = np.array([1, 2, 3, 2, 1], np.float32) / 3.0
    params = MixedFreqParams(
        lam=jnp.ones((N, r), xz.dtype),
        R=jnp.ones(N, xz.dtype),
        A=jnp.concatenate(
            [0.7 * jnp.eye(r, dtype=xz.dtype)[None], jnp.zeros((p - 1, r, r), xz.dtype)]
        ),
        Q=jnp.eye(r, dtype=xz.dtype),
        agg=jnp.asarray(agg),
    )
    stats = compute_panel_stats(xz, m)
    ms = _min_wall(lambda: em_step_mf_stats(params, xz, m, stats))
    assert ms < BUDGET_EM_MF_MS, (
        f"mixed-frequency EM step regressed: {ms:.1f} ms > "
        f"{BUDGET_EM_MF_MS} ms budget at (T,N,r,p)=(672,139,4,5)"
    )


def test_budget_has_teeth():
    """A deliberate 2x slowdown of the measured quantity fails the lane:
    the budgets sit at ~1.6x calibration, so doubling any calibrated time
    exceeds its budget (sanity-check the arithmetic stays that way)."""
    for budget, calibrated in (
        (BUDGET_EM_LARGE_MS, 65.0),
        (BUDGET_ALS_LARGE_MS, 330.0),
        (BUDGET_EM_REF_MS, 20.0),
        (BUDGET_EM_MF_MS, 68.0),
    ):
        assert 2.0 * calibrated > budget, (budget, calibrated)


def test_steady_tail_hlo_is_factorization_free():
    """The steady-state constant-gain tail must compile to pure linear
    algebra: no cholesky / triangular_solve in the stableHLO and no
    factorization kernels (potrf/trsm) in the compiled program, in both
    the lax.scan and the block-parallel formulations.  This is the whole
    point of the fast path — a tail step is one matvec (or one matmul per
    block) where every exact method pays two factorizations per step."""
    from dynamic_factor_models_tpu.models.steady import (
        linear_recursion,
        steady_tail,
    )

    k, q, Tt = 16, 4, 176
    dt = jnp.float32
    rng = np.random.default_rng(0)
    arrs = dict(
        Tm=jnp.asarray(rng.standard_normal((k, k)), dt),
        Cq=jnp.asarray(rng.standard_normal((q, q)), dt),
        Pu_qq=jnp.asarray(np.eye(q), dt),
        K=jnp.asarray(rng.standard_normal((k, q)), dt),
        Abar=jnp.asarray(0.05 * rng.standard_normal((k, k)), dt),
        b=jnp.asarray(rng.standard_normal((Tt, q)), dt),
        s_init=jnp.zeros(k, dt),
        n_obs=jnp.ones(Tt, dt),
        ld=jnp.asarray(1.0, dt),
    )
    for block in (0, 32):
        fn = jax.jit(
            lambda Tm, Cq, Pu_qq, K, Abar, b, s_init, n_obs, ld, _b=block: (
                steady_tail(Tm, Cq, Pu_qq, K, Abar, b, s_init, n_obs, ld, block=_b)
            )
        )
        lowered = fn.lower(*arrs.values())
        hlo = lowered.as_text()
        assert "cholesky" not in hlo, f"cholesky in steady tail (block={block})"
        assert "triangular" not in hlo, (
            f"triangular_solve in steady tail (block={block})"
        )
        compiled = lowered.compile().as_text().lower()
        for op in ("potrf", "trsm", "cholesky", "triangular"):
            assert op not in compiled, (
                f"factorization kernel {op!r} in compiled steady tail "
                f"(block={block})"
            )
        # and the recursion primitive alone, same property
        rec = jax.jit(
            lambda M, g, s0, _b=block: linear_recursion(M, g, s0, block=_b)
        ).lower(arrs["Abar"], arrs["b"] @ arrs["K"].T, arrs["s_init"])
        assert "cholesky" not in rec.as_text()
        assert "triangular" not in rec.as_text()


def test_sequential_program_unchanged_by_steady_path():
    """Requesting the steady path must not perturb the default program:
    the stableHLO of `em_step_stats` at reference scale is byte-identical
    before and after the steady machinery compiles and runs."""
    from dynamic_factor_models_tpu.models.ssm import (
        SteadyEMState,
        _steady_step_for,
        compute_panel_stats,
        em_step_stats,
    )

    xz, m = _panel(224, 139, 0.0, seed=3)
    params = _ssm_params(139, 4, 4)
    stats = compute_panel_stats(xz, m)
    before = em_step_stats.lower(params, xz, m, stats).as_text()
    # exercise the steady path end to end (compile + execute)
    step = _steady_step_for(48, 0)
    st = SteadyEMState(
        params,
        jnp.zeros((16, 16), xz.dtype),
        jnp.asarray(0, jnp.int32),
    )
    jax.block_until_ready(step(st, xz, m, stats))
    after = em_step_stats.lower(params, xz, m, stats).as_text()
    assert before == after, "sequential EM program changed by steady path"


def test_guarded_loop_has_zero_per_iteration_host_syncs():
    """ISSUE-8 tentpole (b): the guarded EM while-loop — healthy OR
    jitter-recovering — is ONE compiled program with no device->host
    transfer anywhere in it.  Pinned on the program text: stableHLO and
    the compiled module contain no infeed/outfeed/host callback (CPU
    lapack custom-calls are fine — they run in-process on the device
    buffers), and the same holds with a transient fault injection baked
    in, whose jitter/jitter_grown recovery is now in-trace
    (guards.N_TRACED_RUNGS)."""
    from dynamic_factor_models_tpu.models.emloop import (
        _em_while_guarded_jit,
        _fresh_guarded_carry,
    )
    from dynamic_factor_models_tpu.models.ssm import (
        compute_panel_stats,
        em_step_stats,
    )

    xz, m = _panel(60, 12, 0.1, seed=4)
    params = _ssm_params(12, 2, 1)
    stats = compute_panel_stats(xz, m)
    ld = jnp.result_type(float)
    tol = jnp.asarray(1e-6, ld)
    carry = _fresh_guarded_carry(params, tol, 16)
    gloop = _em_while_guarded_jit(False)
    for inj in (0, 3):  # clean program AND transient-nan\@3 program
        lowered = gloop.lower(
            em_step_stats, carry, (xz, m, stats), tol,
            jnp.asarray(1e-3, ld), 16, jnp.asarray(16, jnp.int32),
            0, inj, 0,
        )
        for text in (lowered.as_text(), lowered.compile().as_text().lower()):
            for op in ("infeed", "outfeed", "callback", "host_transfer"):
                assert op not in text, (
                    f"host sync {op!r} in guarded loop (inject_nan_at={inj})"
                )


def test_jitter_recovered_run_completes_in_one_dispatch(monkeypatch):
    """Behavioral twin of the HLO pin: a run whose only fault is cured by
    the traced jitter rungs must dispatch the guarded while-loop exactly
    once — the host ladder never re-enters."""
    from dynamic_factor_models_tpu.models import emloop
    from dynamic_factor_models_tpu.models.ssm import (
        compute_panel_stats,
        em_step_stats,
    )
    from dynamic_factor_models_tpu.utils import faults, guards

    calls = []
    orig = emloop._em_while_guarded_jit

    def counting(donate):
        g = orig(donate)

        def wrapped(*a, **k):
            calls.append(1)
            return g(*a, **k)

        return wrapped

    monkeypatch.setattr(emloop, "_em_while_guarded_jit", counting)
    xz, m = _panel(60, 12, 0.1, seed=4)
    params = _ssm_params(12, 2, 1)
    stats = compute_panel_stats(xz, m)
    with faults.inject("nan_estep@3"):
        res = emloop.run_em_loop(
            em_step_stats, params, (xz, m, stats), 1e-8, 20, guard=True
        )
    assert res.health == guards.HEALTH_OK
    assert res.recoveries == 1
    assert len(calls) == 1, (
        f"jitter-recovered run took {len(calls)} dispatches, expected 1"
    )


@pytest.mark.telemetry
def test_disabled_telemetry_path_is_free(monkeypatch):
    """The observability layer must cost nothing when unconfigured: every
    `run_record()` call returns the same no-op singleton (no per-call
    allocation), a disabled with-block leaves the registry untouched, and
    ~20k disabled record cycles complete in well under the time one real
    JSONL write would take."""
    from dynamic_factor_models_tpu.utils import telemetry as T

    monkeypatch.delenv("DFM_TELEMETRY", raising=False)
    monkeypatch.delenv("DFM_PROFILE_DIR", raising=False)
    monkeypatch.setattr(T, "_explicit_enabled", None)
    monkeypatch.setattr(T, "_explicit_sink", None)
    assert not T.enabled()

    a = T.run_record("x", config={"k": 1})
    b = T.run_record("y")
    assert a is b and a.active is False

    before = T.snapshot()
    t0 = time.perf_counter()
    for _ in range(20_000):
        with T.run_record("z") as rec:
            rec.set(n_iter=1)
            rec.add_phase("p", 0.0)
    dt = time.perf_counter() - t0
    after = T.snapshot()
    assert after["counters"] == before["counters"]
    assert after["timers"] == before["timers"]
    # 20k no-op cycles: generous 0.5 s ceiling (~25 us/cycle) — a path that
    # accidentally allocates records or touches the filesystem blows this
    assert dt < 0.5, f"disabled-path run_record cost {dt:.3f}s for 20k cycles"


def test_collapsed_ar_scan_body_hlo_is_n_free():
    """ISSUE-10 acceptance pin: no scan body in any collapsed large-N
    kernel carries an N-sized operand.  N = 1999 — prime and not a compile
    bucket, so a leaked cross-section dimension cannot masquerade as a
    legitimate shape — and the match is on stableHLO shape tokens
    ([<x]1999x), immune to float literals like 1.999e0.  Kernels pinned:
    the quasi-differenced AR EM step, the collapsed conditional and draw
    fans (both observables variants), the rank-1-increment news path, and
    the collapsed simulation smoother.  All O(N) work — collapse GEMMs,
    M-step Grams, observable projections — must lower OUTSIDE the whiles:
    that is the whole N-free-per-step contract."""
    import re

    from dynamic_factor_models_tpu.models import bayes, news
    from dynamic_factor_models_tpu.models import ssm_ar as ar
    from dynamic_factor_models_tpu.models.ssm import SSMParams
    from dynamic_factor_models_tpu.ops.masking import fillz, mask_of
    from dynamic_factor_models_tpu.scenarios import fanout

    N, T, r, h, S, D = 1999, 48, 2, 4, 3, 2
    token = re.compile(r"[<x]%dx" % N)

    def while_bodies(hlo):
        bodies, start = [], 0
        while True:
            i = hlo.find("stablehlo.while", start)
            if i < 0:
                break
            j = hlo.find("{", i)
            depth, k = 1, j + 1
            while depth and k < len(hlo):
                depth += {"{": 1, "}": -1}.get(hlo[k], 0)
                k += 1
            bodies.append(hlo[i:k])
            start = k
        return bodies

    def assert_n_free(lowered, name):
        bodies = while_bodies(lowered.as_text())
        assert bodies, f"{name}: no while loops — scan lowering changed?"
        for body in bodies:
            leak = token.search(body)
            assert leak is None, (
                f"{name}: N-sized operand inside a scan body near "
                f"...{body[max(0, leak.start() - 120):leak.start() + 60]}..."
            )

    rng = np.random.default_rng(0)
    dt = jnp.float32
    x = rng.standard_normal((T, N)).astype(np.float32)
    x[:3, 0] = np.nan
    xj = jnp.asarray(x)
    xz, m = fillz(xj), mask_of(xj)

    arp = ar.SSMARParams(
        lam=jnp.asarray(0.3 * rng.standard_normal((N, r)), dt),
        phi=jnp.zeros(N, dt),
        sigv2=jnp.ones(N, dt),
        A=0.5 * jnp.eye(r, dtype=dt)[None],
        Q=jnp.eye(r, dtype=dt),
    )
    qd = ar.compute_qd_stats(xz, m)
    assert_n_free(ar.em_step_ar_qd.lower(arp, xz, qd), "em_step_ar_qd")

    params = SSMParams(
        lam=arp.lam, R=jnp.ones(N, dt), A=arp.A, Q=arp.Q
    )
    cond = np.full((S, h, N), np.nan, np.float32)
    cond[:, 0, 0] = 1.0
    stats = fanout._collapse_fan_stats(params, xj, h, cond)
    keys = jax.random.split(jax.random.PRNGKey(0), S * D).reshape(S, D, 2)
    for obs in (True, False):
        assert_n_free(
            fanout._conditional_fan_collapsed_impl.lower(
                params, *stats, horizon=h, observables=obs
            ),
            f"conditional_fan_collapsed(observables={obs})",
        )
        assert_n_free(
            fanout._draw_fan_collapsed_impl.lower(
                params, *stats, keys, horizon=h, observables=obs
            ),
            f"draw_fan_collapsed(observables={obs})",
        )

    mf = m.astype(dt)
    assert_n_free(
        news._nowcast_paths_multi_collapsed.lower(
            params, xz, mf,
            jnp.asarray([1, 2]), jnp.asarray([0, 0]),
            jnp.asarray([T - 1]), jnp.asarray([1]),
        ),
        "nowcast_news_collapsed",
    )
    assert_n_free(
        bayes._simulation_smoother_collapsed_entry.lower(
            params, xz, mf, jax.random.PRNGKey(0)
        ),
        "simulation_smoother_collapsed",
    )


def test_derived_kernel_registry_size_is_pinned():
    """Kernel-count regression guard for the derived AOT plan: the
    transform stack must neither leak orphan registry entries (a stack
    enumerated twice, or an alias nobody dispatches) nor silently drop a
    kernel a call site still asks for.  Counts are exact, not bounds —
    adding a kernel on purpose means updating this pin in the same PR."""
    import numpy as _np

    from dynamic_factor_models_tpu.models import transforms as tfm
    from dynamic_factor_models_tpu.utils import compile as cc

    # default spec: the 8 EM-family aliases live at t_star=None plus the
    # two non-EM cores
    spec = cc.CompileSpec(T=60, N=12, r=2, p=1,
                          dtype=str(_np.dtype(float)), max_em_iter=4)
    assert len(tfm.enumerate_stacks(spec)) == 8
    assert len(cc._kernel_plan(spec)) == 10

    # maximal spec: every historical kernel (steady + sharded + batched)
    full = cc.CompileSpec(
        T=60, N=12, r=2, p=1, dtype=str(_np.dtype(float)), max_em_iter=4,
        t_star=16, n_shards=2, em_batch=2,
        kernels=cc.CompileSpec.kernels
        + ("em_step_sharded", "em_loop_guarded@sharded"),
    )
    assert len(tfm.enumerate_stacks(full)) == 14
    assert len(cc._kernel_plan(full)) == 16

    # the four composed opt-ins add exactly four entries, nothing else
    composed = full.kernels + (
        "em_step_collapsed", "em_step_ar_steady",
        "em_step_ar_sharded", "em_step_ar_all",
    )
    full_c = cc.CompileSpec(
        T=60, N=12, r=2, p=1, dtype=str(_np.dtype(float)), max_em_iter=4,
        t_star=16, n_shards=2, em_batch=2, kernels=composed,
    )
    assert len(cc._kernel_plan(full_c)) == 20

    # t_blocks opts the parallel-in-time family in BY NAME: the three tp
    # kernels add exactly three entries on top of the maximal spec (the
    # time x shard product needs n_shards too), nothing else moves
    full_tp = cc.CompileSpec(
        T=60, N=12, r=2, p=1, dtype=str(_np.dtype(float)), max_em_iter=4,
        t_star=16, n_shards=2, em_batch=2, t_blocks=4,
        kernels=full.kernels
        + ("em_step_tp", "em_step_ar_tp", "em_step_tp_sharded"),
    )
    assert len(tfm.enumerate_stacks(full_tp)) == 17
    assert len(cc._kernel_plan(full_tp)) == 19
    # t_blocks without the kernel names is inert — same set as `full`
    silent_tp = cc.CompileSpec(
        T=60, N=12, r=2, p=1, dtype=str(_np.dtype(float)), max_em_iter=4,
        t_star=16, n_shards=2, em_batch=2, t_blocks=4,
        kernels=full.kernels,
    )
    assert len(tfm.enumerate_stacks(silent_tp)) == 14

    # particle_count opts the SMC family in: exactly one plan per
    # AOT-able particle model (tvp is excluded — its aux carries a
    # panel-length factor path, which would key the executable on data
    # rather than shape), nothing else moves
    assert tfm.enumerate_smc(spec) == []
    pspec = cc.CompileSpec(
        T=60, N=12, r=2, p=1, dtype=str(_np.dtype(float)), max_em_iter=4,
        particle_count=256, scenario_paths=2, scenario_horizon=4,
    )
    smc_entries = tfm.enumerate_smc(pspec)
    assert [e.key for e in smc_entries] == [
        "smc_filter@lg", "smc_filter@sv", "smc_filter@msdfm",
    ]
    assert len(cc._kernel_plan(pspec)) == len(cc._kernel_plan(spec)) + 3


# ---------------------------------------------------------------------------
# PR-12 acceptance pins: request observability must be free on-device and
# within the host envelope bar
# ---------------------------------------------------------------------------


@pytest.mark.telemetry
def test_instrumented_clean_path_hlo_is_byte_identical(tmp_path, monkeypatch):
    """The request-observability layer (trace spans, HDR histograms, SLO
    counters) is host-side only: lowering the tick and nowcast programs
    with telemetry fully live — sink configured, a request span open,
    histograms populated — must produce byte-identical StableHLO to the
    uninstrumented lowering.  A deterministic pin, unlike the wall-clock
    envelope bar below."""
    from dynamic_factor_models_tpu.serving import engine as _eng
    from dynamic_factor_models_tpu.serving.online import _nowcast, _tick
    from dynamic_factor_models_tpu.utils import telemetry as T

    monkeypatch.delenv("DFM_TELEMETRY", raising=False)
    monkeypatch.delenv("DFM_PROFILE_DIR", raising=False)
    monkeypatch.setattr(T, "_explicit_enabled", None)
    monkeypatch.setattr(T, "_explicit_sink", None)
    assert not T.enabled()

    rng = np.random.default_rng(0)
    eng = _eng.ServingEngine(max_em_iter=4)
    eng.register("t", rng.standard_normal((40, 8)))
    ten = eng._tenants["t"]
    row = jnp.asarray(rng.standard_normal(8))
    mask = jnp.ones(8, bool)

    off_tick = _tick.lower(ten.model, ten.state, row, mask).as_text()
    off_now = _nowcast.lower(ten.model, ten.state.s).as_text()

    monkeypatch.setenv("DFM_TELEMETRY", str(tmp_path / "t.jsonl"))
    monkeypatch.setattr(T, "_explicit_enabled", None)
    T.reset()
    assert T.enabled()
    assert eng.handle(
        {"kind": "tick", "tenant": "t", "x": np.asarray(row)}
    ).ok
    assert eng.handle({"kind": "nowcast", "tenant": "t"}).ok
    with T.trace_span("serving.request", seed="hlo-pin", kind="tick"):
        on_tick = _tick.lower(ten.model, ten.state, row, mask).as_text()
        on_now = _nowcast.lower(ten.model, ten.state.s).as_text()

    assert on_tick == off_tick
    assert on_now == off_now


@pytest.mark.telemetry
def test_clean_path_envelope_overhead_within_bar(monkeypatch):
    """PR-12 acceptance bar: the full request envelope — validation,
    breaker, histogram + SLO accounting, the single disabled-telemetry
    probe — costs <= 5% of the bare online_tick wall (device program
    stubbed, same protocol as bench.py's load/chaos sections).  The
    fraction is computed per round and the min over rounds taken: the
    numerator and denominator share each round's machine noise, and the
    min rejects scheduler spikes."""
    from dynamic_factor_models_tpu.serving import engine as _eng
    from dynamic_factor_models_tpu.serving.online import online_tick
    from dynamic_factor_models_tpu.utils import telemetry as T

    monkeypatch.delenv("DFM_TELEMETRY", raising=False)
    monkeypatch.delenv("DFM_PROFILE_DIR", raising=False)
    monkeypatch.setattr(T, "_explicit_enabled", None)
    monkeypatch.setattr(T, "_explicit_sink", None)
    assert not T.enabled()

    rng = np.random.default_rng(1)
    eng = _eng.ServingEngine(max_em_iter=4)
    eng.register("t", rng.standard_normal((40, 8)))
    ten = eng._tenants["t"]
    model, st_pin = ten.model, ten.state
    n = 1000
    xr = [rng.standard_normal(8) for _ in range(n)]

    def handle_loop():
        for i in range(n):
            eng.handle({"kind": "tick", "tenant": "t", "x": xr[i]})

    def raw_loop():
        s = st_pin
        for i in range(n):
            m = np.isfinite(xr[i])
            s = online_tick(model, s, np.where(m, xr[i], 0.0), m)
        return jax.block_until_ready(s)

    raw_loop()
    handle_loop()  # warm both paths (compiles) before the clock starts
    real_tick = _eng.online_tick
    _eng.online_tick = lambda model, state, x, m: st_pin
    try:
        fracs = []
        for _ in range(3):
            t0 = time.perf_counter()
            raw_loop()
            wall_r = time.perf_counter() - t0
            t0 = time.perf_counter()
            handle_loop()
            wall_e = time.perf_counter() - t0
            fracs.append(wall_e / wall_r)
    finally:
        _eng.online_tick = real_tick
    best = min(fracs)
    assert best < 0.05, (
        f"clean-path envelope {100 * best:.1f}% of raw tick wall "
        f"(rounds: {[round(f, 4) for f in fracs]})"
    )


# ---------------------------------------------------------------------------
# PR-13 pin: the eviction layer is host-side only — the clean-path tick
# program is byte-identical with a resident budget active, and the
# request envelope (now including the LRU touch) stays within the bar
# ---------------------------------------------------------------------------


@pytest.mark.telemetry
def test_eviction_layer_clean_path_hlo_is_byte_identical(tmp_path):
    """Lowering the tick program from a BUDGETED engine (store + LRU
    accounting live) must produce byte-identical StableHLO to the
    unbudgeted lowering: eviction bookkeeping is dict + counter work on
    the host and never enters the compiled program."""
    from dynamic_factor_models_tpu.serving import engine as _eng
    from dynamic_factor_models_tpu.serving.online import _tick

    rng = np.random.default_rng(5)
    pan = rng.standard_normal((40, 8))
    row = jnp.asarray(rng.standard_normal(8))
    mask = jnp.ones(8, bool)

    plain = _eng.ServingEngine(max_em_iter=4)
    plain.register("t", pan)
    ten = plain._tenants["t"]
    hlo_plain = _tick.lower(ten.model, ten.state, row, mask).as_text()

    budgeted = _eng.ServingEngine(
        max_em_iter=4, store_dir=str(tmp_path / "store"),
        resident_tenants=1,
    )
    budgeted.register("t", pan)
    assert budgeted.handle(
        {"kind": "tick", "tenant": "t", "x": np.asarray(row)}
    ).ok
    ten_b = budgeted._tenants["t"]
    hlo_budget = _tick.lower(ten_b.model, ten_b.state, row, mask).as_text()
    assert hlo_budget == hlo_plain


@pytest.mark.telemetry
def test_eviction_layer_adds_within_bar_to_store_envelope(
    tmp_path, monkeypatch
):
    """The LRU layer's ADDED host cost on the clean path — one dict pop
    + re-insert per `_lookup`, the dirty counter on commit — is <= 5%
    of the store-backed request envelope: a budgeted engine (budget
    wide enough that no eviction fires) races an unbudgeted one over
    the identical workload, with the device program and the write-ahead
    append stubbed so both loops measure pure host bookkeeping.  (The
    unbudgeted no-store envelope keeps its own absolute <= 5% bar in
    the PR-12 test above, which runs with this layer present.)"""
    from dynamic_factor_models_tpu.serving import engine as _eng
    from dynamic_factor_models_tpu.serving.journal import TickJournal
    from dynamic_factor_models_tpu.utils import telemetry as T

    monkeypatch.delenv("DFM_TELEMETRY", raising=False)
    monkeypatch.delenv("DFM_PROFILE_DIR", raising=False)
    monkeypatch.setattr(T, "_explicit_enabled", None)
    monkeypatch.setattr(T, "_explicit_sink", None)
    assert not T.enabled()

    rng = np.random.default_rng(6)
    pan = rng.standard_normal((40, 8))
    plain = _eng.ServingEngine(
        max_em_iter=4, store_dir=str(tmp_path / "plain")
    )
    budget = _eng.ServingEngine(
        max_em_iter=4, store_dir=str(tmp_path / "budget"),
        resident_tenants=8,
    )
    assert budget._budget_on and not plain._budget_on
    for e in (plain, budget):
        e.register("t", pan)
    st_pin = plain._tenants["t"].state
    n = 1000
    xr = [rng.standard_normal(8) for _ in range(n)]

    def loop(e):
        for i in range(n):
            e.handle({"kind": "tick", "tenant": "t", "x": xr[i]})

    loop(plain)
    loop(budget)  # warm both paths before the clock starts
    real_tick = _eng.online_tick
    _eng.online_tick = lambda model, state, x, m: st_pin
    monkeypatch.setattr(TickJournal, "append", lambda self, t, x, m: None)
    try:
        fracs = []
        for _ in range(3):
            t0 = time.perf_counter()
            loop(plain)
            wall_p = time.perf_counter() - t0
            t0 = time.perf_counter()
            loop(budget)
            wall_b = time.perf_counter() - t0
            fracs.append(wall_b / wall_p)
    finally:
        _eng.online_tick = real_tick
    best = min(fracs)
    assert best < 1.05, (
        f"eviction layer adds {100 * (best - 1):.1f}% to the store "
        f"envelope (rounds: {[round(f, 4) for f in fracs]})"
    )
