"""Cross-section sharding (PR-8 tentpole): the N-axis shard_map'd EM step
must match the single-device program at numerical precision, with padding
provably inert at awkward shard counts.

The exactness argument, pinned numerically here: the Jungbacker-Koopman
collapse statistics (C, b) and the log-likelihood corrections (ld_R via
the fused log-R column, the Sxx/R quadratic) are all SUMS over series, so
a shard computes its partial on its N/n_dev slice and one all-reduce
(`ops.pallas_gram.ring_allreduce`; `lax.psum` on this CPU mesh) restores
the full-panel values bit-for-bit up to reduction-order roundoff.  The
Kalman scan and factor-VAR moments are N-free and run replicated; the
M-step's per-series solves are embarrassingly shard-local.  Padded series
(zero loadings, unit R, all-False mask — `compile.pad_ssm_params` /
`pad_panel`) contribute zero to every sum and land back on zero loadings
after the M-step, so uneven N costs padding memory, never accuracy.

Runs on the forced 8-device CPU platform (tests/conftest.py) — the
`multidevice` marker documents the requirement and skips with a
diagnostic if the devices did not materialize.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.dfm import DFMConfig
from dynamic_factor_models_tpu.models.ssm import (
    compute_panel_stats,
    em_step_sharded,
    em_step_stats,
    estimate_dfm_em,
)
from dynamic_factor_models_tpu.parallel.mesh import rep_pad, series_pad
from dynamic_factor_models_tpu.utils.compile import (
    pad_panel,
    pad_ssm_params,
)

PARITY_ATOL = 1e-10  # the ISSUE-8 acceptance bar (x64 CPU mesh)


def _panel(T, N, r=2, seed=0, missing=0.15):
    rng = np.random.default_rng(seed)
    f = rng.standard_normal((T, r))
    lam = rng.standard_normal((N, r))
    x = f @ lam.T + 0.5 * rng.standard_normal((T, N))
    # ragged missingness off the first complete block (ALS init needs it)
    x[rng.random((T, N)) < missing * (np.arange(N) >= r + 4)] = np.nan
    return x


def _prep_padded(T, N, n_shards, r=2, p=1, seed=0):
    """Padded (params, xz, mask, stats) exactly as estimate_dfm_em's
    sharded branch builds them (inert-series contract included)."""
    x = _panel(T, N, r=r, seed=seed)
    m = ~np.isnan(x)
    xz = jnp.asarray(np.where(m, x, 0.0))
    mask = jnp.asarray(m)
    Np = series_pad(N, n_shards)
    xz_p, mask_p, tw = pad_panel(xz, mask, T, Np)
    rng = np.random.default_rng(seed + 1)
    from dynamic_factor_models_tpu.models.ssm import SSMParams

    params = SSMParams(
        lam=jnp.asarray(0.3 * rng.standard_normal((N, r))),
        R=jnp.ones(N, xz.dtype),
        A=jnp.concatenate(
            [0.5 * jnp.eye(r, dtype=xz.dtype)[None],
             jnp.zeros((p - 1, r, r), xz.dtype)]
        ),
        Q=jnp.eye(r, dtype=xz.dtype),
    )
    params_p = pad_ssm_params(params, Np)
    stats = compute_panel_stats(xz_p, mask_p)._replace(tw=tw)
    return params_p, xz_p, mask_p, stats


def _max_leaf_diff(a, b):
    return max(
        float(jnp.max(jnp.abs(x - y))) if x.size else 0.0
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def test_series_pad_awkward_counts():
    assert series_pad(37, 8) == 40
    assert series_pad(40, 8) == 40
    assert series_pad(1, 8) == 8
    assert series_pad(16384, 8) == 16384
    # n_shards <= 1: identity (no mesh, no padding)
    assert series_pad(37, 1) == 37
    assert series_pad(37, 0) == 37


def test_rep_pad_awkward_counts():
    # the replication-axis twin of series_pad, same awkward shapes
    assert rep_pad(9, 8) == 16
    assert rep_pad(8, 8) == 8
    assert rep_pad(1, 8) == 8
    assert rep_pad(17, 8, bucket=0) == 24
    assert rep_pad(5, 1) == 5


@pytest.mark.multidevice
def test_sharded_step_matches_single_device_uneven_n():
    """One sharded EM step over 8 devices == the single-device step at
    <= 1e-10, at an N (37 -> padded 40) that does NOT divide evenly."""
    params, xz, mask, stats = _prep_padded(60, 37, 8, r=3, p=2, seed=3)
    p1, ll1 = em_step_stats(params, xz, mask, stats)
    p8, ll8 = em_step_sharded(params, xz, mask, stats, 8)
    assert abs(float(ll1) - float(ll8)) <= PARITY_ATOL
    assert _max_leaf_diff(p1, p8) <= PARITY_ATOL


@pytest.mark.multidevice
def test_sharded_iteration_chain_stays_on_parity():
    """Parity must hold ITERATIVELY, not just for one step — roundoff
    from a reordered reduction would compound across EM iterations."""
    params, xz, mask, stats = _prep_padded(50, 21, 8, seed=5)
    p1 = p8 = params
    for _ in range(5):
        p1, ll1 = em_step_stats(p1, xz, mask, stats)
        p8, ll8 = em_step_sharded(p8, xz, mask, stats, 8)
    assert abs(float(ll1) - float(ll8)) <= PARITY_ATOL
    assert _max_leaf_diff(p1, p8) <= PARITY_ATOL


@pytest.mark.multidevice
def test_sharded_padding_is_inert():
    """Padded series must be exactly inert: zero loadings in, zero
    loadings out (their Sxf rows are identically zero), and the REAL
    series' parameters identical whether the padding exists or not."""
    T, N, ns = 48, 11, 8  # pads 11 -> 16: five inert series
    params, xz, mask, stats = _prep_padded(T, N, ns, seed=7)
    Np = params.lam.shape[0]
    assert Np == 16
    p8 = params
    for _ in range(3):
        p8, _ = em_step_sharded(p8, xz, mask, stats, ns)
        # padding stays exactly dark across iterations
        np.testing.assert_array_equal(np.asarray(p8.lam[N:]), 0.0)
    # real-series block: identical to the single-device step on the SAME
    # padded inputs (transitively, to the unpadded run — the bucketing
    # tests pin pad-vs-unpadded)
    p1 = params
    for _ in range(3):
        p1, _ = em_step_stats(p1, xz, mask, stats)
    assert _max_leaf_diff(p1, p8) <= PARITY_ATOL


@pytest.mark.multidevice
def test_single_shard_mesh_matches_unsharded():
    """n_dev=1 degenerate mesh: shard_map over one device is the same
    program (psum over a singleton axis is identity)."""
    params, xz, mask, stats = _prep_padded(40, 9, 1, seed=11)
    p1, ll1 = em_step_stats(params, xz, mask, stats)
    ps, lls = em_step_sharded(params, xz, mask, stats, 1)
    assert abs(float(ll1) - float(lls)) <= PARITY_ATOL
    assert _max_leaf_diff(p1, ps) <= PARITY_ATOL


@pytest.mark.multidevice
def test_estimate_dfm_em_sharded_matches_unsharded_end_to_end():
    """The acceptance pin: estimate_dfm_em(n_shards=8) == n_shards=None
    at <= 1e-10 on params AND the loglik path, full guarded run."""
    T, N = 70, 13
    x = _panel(T, N, seed=2)
    cfg = DFMConfig(nfac_u=2, n_factorlag=1)
    base = estimate_dfm_em(x, np.ones(N), 0, T - 1, cfg, max_em_iter=12)
    shrd = estimate_dfm_em(
        x, np.ones(N), 0, T - 1, cfg, max_em_iter=12, n_shards=8
    )
    assert shrd.params.lam.shape == base.params.lam.shape  # unpadded back
    assert shrd.n_iter == base.n_iter
    assert shrd.converged == base.converged
    assert _max_leaf_diff(base.params, shrd.params) <= PARITY_ATOL
    n = base.n_iter
    np.testing.assert_allclose(
        np.asarray(shrd.loglik_path[:n]), np.asarray(base.loglik_path[:n]),
        atol=PARITY_ATOL, rtol=0,
    )
    np.testing.assert_allclose(
        np.asarray(shrd.factors), np.asarray(base.factors), atol=1e-8
    )


@pytest.mark.multidevice
def test_estimate_n_shards_one_is_the_unsharded_path():
    T, N = 50, 9
    x = _panel(T, N, seed=4)
    cfg = DFMConfig(nfac_u=2, n_factorlag=1)
    base = estimate_dfm_em(x, np.ones(N), 0, T - 1, cfg, max_em_iter=8)
    one = estimate_dfm_em(
        x, np.ones(N), 0, T - 1, cfg, max_em_iter=8, n_shards=1
    )
    assert _max_leaf_diff(base.params, one.params) == 0.0


def test_n_shards_validation():
    x = _panel(40, 8, seed=6)
    cfg = DFMConfig(nfac_u=2, n_factorlag=1)
    with pytest.raises(ValueError, match="sequential"):
        estimate_dfm_em(
            x, np.ones(8), 0, 39, cfg, method="sqrt", n_shards=8
        )
    with pytest.raises(ValueError, match="gram_dtype"):
        estimate_dfm_em(
            x, np.ones(8), 0, 39, cfg, gram_dtype="bfloat16", n_shards=8
        )
    with pytest.raises(ValueError, match="devices|device"):
        estimate_dfm_em(
            x, np.ones(8), 0, 39, cfg, n_shards=jax.device_count() + 1
        )


def _mf_panel(T, N, n_quarterly, seed=8):
    """Monthly panel whose last n_quarterly columns are quarterly: observed
    only in quarter-end months (t % 3 == 2), NaN elsewhere."""
    x = np.array(_panel(T, N, seed=seed))
    is_q = np.zeros(N, bool)
    is_q[N - n_quarterly :] = True
    not_qend = (np.arange(T) % 3) != 2
    x[np.ix_(not_qend, np.nonzero(is_q)[0])] = np.nan
    return x, is_q


@pytest.mark.multidevice
def test_mixed_freq_sharded_matches_sequential():
    """The lifted refusal: estimate_mixed_freq_dfm(n_shards=8) must match
    the sequential run at 1e-10 — N=10 pads to 16 over the 8-device mesh,
    so this also exercises the inert quarterly/monthly series padding
    under the period-3 mask cycle."""
    from dynamic_factor_models_tpu.models.mixed_freq import (
        estimate_mixed_freq_dfm,
    )

    T, N = 48, 10
    x, is_q = _mf_panel(T, N, n_quarterly=4)
    base = estimate_mixed_freq_dfm(x, is_q, r=2, max_em_iter=6)
    shrd = estimate_mixed_freq_dfm(x, is_q, r=2, max_em_iter=6, n_shards=8)
    assert shrd.params.lam.shape == base.params.lam.shape  # unpadded
    assert _max_leaf_diff(base.params, shrd.params) < PARITY_ATOL
    n = min(len(base.loglik_path), len(shrd.loglik_path))
    assert n >= 1
    np.testing.assert_allclose(
        np.asarray(shrd.loglik_path[:n]), np.asarray(base.loglik_path[:n]),
        atol=PARITY_ATOL, rtol=0,
    )
    np.testing.assert_allclose(
        np.asarray(shrd.factors), np.asarray(base.factors), atol=1e-8
    )


def test_mixed_freq_n_shards_validation():
    from dynamic_factor_models_tpu.models.mixed_freq import (
        estimate_mixed_freq_dfm,
    )

    x, is_q = _mf_panel(36, 6, n_quarterly=2)
    with pytest.raises(ValueError, match="gram_dtype"):
        estimate_mixed_freq_dfm(
            x, is_q, r=1, gram_dtype="bfloat16", n_shards=8
        )
    with pytest.raises(ValueError, match="devices|device"):
        estimate_mixed_freq_dfm(
            x, is_q, r=1, n_shards=jax.device_count() + 1
        )


@pytest.mark.multidevice
@pytest.mark.serving
def test_refit_sequential_sharded_matches_unsharded():
    """serving/batch.py mesh pickup: a sharded per-tenant refit returns
    the same params as the plain sequential reference."""
    from dynamic_factor_models_tpu.serving.batch import (
        RefitRequest,
        refit_sequential,
    )
    from dynamic_factor_models_tpu.models.ssm import SSMParams

    reqs = []
    for i, (T, N) in enumerate([(40, 9), (40, 13)]):
        x = _panel(T, N, seed=20 + i)
        m = ~np.isnan(x)
        r = 2
        rng = np.random.default_rng(30 + i)
        params = SSMParams(
            lam=jnp.asarray(0.3 * rng.standard_normal((N, r))),
            R=jnp.ones(N),
            A=0.5 * jnp.eye(r)[None],
            Q=jnp.eye(r),
        )
        reqs.append(
            RefitRequest(f"t{i}", jnp.asarray(np.where(m, x, 0.0)),
                         jnp.asarray(m), params)
        )
    base = refit_sequential(reqs, max_em_iter=6)
    shrd = refit_sequential(reqs, max_em_iter=6, n_shards=8)
    for b, s in zip(base, shrd):
        assert s.params.lam.shape == b.params.lam.shape
        assert s.n_iter == b.n_iter
        assert _max_leaf_diff(b.params, s.params) <= PARITY_ATOL
    with pytest.raises(ValueError, match="step"):
        refit_sequential(reqs, step=em_step_stats, n_shards=8)


@pytest.mark.multidevice
def test_compile_spec_sharded_plans_warm_hit():
    """CompileSpec(n_shards=8) AOT-registers the sharded step and the
    guarded loop specialized to it; the second precompile of the same
    spec is served entirely from the in-process registry."""
    from dynamic_factor_models_tpu.utils import compile as cc

    cc.reset_counters()
    spec = cc.CompileSpec(
        T=40, N=16, r=2, p=1, dtype=str(np.dtype(float)),
        kernels=("em_step_sharded", "em_loop_guarded@sharded"),
        max_em_iter=4, n_shards=8,
    )
    r1 = cc.precompile(spec)
    assert not r1["kernels"]["em_step_sharded"]["aot_cached"]
    assert not r1["kernels"]["em_loop_guarded@sharded"]["aot_cached"]
    assert cc.counters()["em_step_sharded"]["compiles"] == 1
    r2 = cc.precompile(spec)
    assert r2["kernels"]["em_step_sharded"]["aot_cached"]
    assert r2["kernels"]["em_loop_guarded@sharded"]["aot_cached"]
    assert r2["compile_s_total"] == 0.0
    assert cc.counters()["em_step_sharded"]["aot_hits"] == 1
    assert cc.counters()["em_loop_guarded"]["aot_hits"] == 1


@pytest.mark.multidevice
@pytest.mark.telemetry
def test_sharded_run_records_mesh_and_summarize_devices_column(
    tmp_path, monkeypatch
):
    """RunRecord carries mesh_shape/n_devices/sharded; summarize renders
    a devices column — '-' for single-device records, the mesh shape for
    sharded ones."""
    from dynamic_factor_models_tpu.utils import telemetry

    path = str(tmp_path / "runs.jsonl")
    # monkeypatch (not disable()) so the process-wide enablement state is
    # RESTORED at teardown — disable() would pin telemetry off and break
    # later DFM_TELEMETRY-driven tests in the same process
    monkeypatch.setattr(telemetry, "_explicit_enabled", None)
    monkeypatch.setattr(telemetry, "_explicit_sink", None)
    telemetry.enable(path)
    T, N = 50, 9
    x = _panel(T, N, seed=9)
    cfg = DFMConfig(nfac_u=2, n_factorlag=1)
    estimate_dfm_em(x, np.ones(N), 0, T - 1, cfg, max_em_iter=5)
    estimate_dfm_em(
        x, np.ones(N), 0, T - 1, cfg, max_em_iter=5, n_shards=8
    )
    recs = [
        r for r in telemetry._load_jsonl(path)
        if r.get("entry") == "estimate_dfm_em"
    ]
    assert len(recs) == 2
    plain, sharded = recs
    assert plain["sharded"] is False and plain["mesh_shape"] is None
    assert sharded["sharded"] is True and sharded["mesh_shape"] == [8]
    assert sharded["n_devices"] == jax.device_count()
    assert telemetry._dev_str(plain) == "-"
    assert telemetry._dev_str(sharded) == "8"
    table = telemetry.summarize(path)
    header = next(
        ln for ln in table.splitlines() if ln.startswith("time")
    )
    assert "dev" in header.split()
