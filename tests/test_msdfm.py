"""Markov-switching DFM (models/msdfm.py): exact single-regime equivalence
with a dense Kalman filter, synthetic regime recovery via the fitted
smoothed probabilities, and the real-panel recession readout (slow)."""

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.msdfm import (
    MSDFMParams,
    fit_ms_dfm,
    kim_filter,
    kim_smoother_probs,
)
from dynamic_factor_models_tpu.ops.masking import mask_of


@pytest.fixture
def rng():
    return np.random.default_rng(17)


def _dense_ms_loglik_single_regime(lam, R, c, phi, x):
    """Reference: scalar-state Kalman with stationary init, observation
    x_t = lam*(c + z_t) + e — computed densely in NumPy with masking."""
    T, N = x.shape
    m, P = 0.0, 1.0 / (1.0 - phi**2)
    ll = 0.0
    for t in range(T):
        obs = ~np.isnan(x[t])
        a, Pp = phi * m, phi**2 * P + 1.0
        lo, Ro = lam[obs], R[obs]
        v = x[t, obs] - lo * c - lo * a
        S = np.outer(lo, lo) * Pp + np.diag(Ro)
        Sinv = np.linalg.inv(S)
        ll += -0.5 * (
            obs.sum() * np.log(2 * np.pi)
            + np.linalg.slogdet(S)[1]
            + v @ Sinv @ v
        )
        K = Pp * (Sinv @ lo)
        m = a + K @ v
        P = Pp * (1.0 - (lo @ K))
    return ll


def test_single_regime_matches_dense_kalman(rng):
    T, N = 60, 5
    lam = rng.standard_normal(N)
    R = 0.3 + rng.random(N)
    c, phi = 0.4, 0.8
    z = np.zeros(T)
    for t in range(1, T):
        z[t] = phi * z[t - 1] + rng.standard_normal()
    x = np.outer(c + z, lam) + np.sqrt(R) * rng.standard_normal((T, N))
    x[rng.random((T, N)) < 0.1] = np.nan

    params = MSDFMParams(
        lam=jnp.asarray(lam),
        R=jnp.asarray(R),
        mu=jnp.asarray([c]),
        phi=jnp.asarray(phi),
        P=jnp.asarray([[1.0]]),
        sigma2=jnp.asarray([1.0]),
    )
    xj = jnp.asarray(x)
    ll, filt, pred, _, _ = kim_filter(params, xj, mask_of(xj))
    ll_ref = _dense_ms_loglik_single_regime(lam, R, c, phi, x)
    # with one regime the Kim collapse is exact: loglik must match the
    # dense filter to float precision
    assert abs(float(ll) - ll_ref) < 1e-6 * (1 + abs(ll_ref)), (
        float(ll),
        ll_ref,
    )
    assert np.allclose(np.asarray(filt), 1.0)
    sm = kim_smoother_probs(params, filt, pred)
    assert np.allclose(np.asarray(sm), 1.0)


def _two_regime_panel(rng, T=400, N=8, S=None, lam=None):
    """Identifiable design: the regime separation (2.5) clearly exceeds
    the stationary sd of the within-regime AR factor (1/sqrt(1-0.3^2)
    ~ 1.05) — with separation ~ the factor sd, maximum likelihood
    genuinely prefers a weak-regime configuration (checked: the ML mode
    beats the true parameters' likelihood on such designs), so a recovery
    test there would test the DGP, not the estimator."""
    P = np.array([[0.92, 0.08], [0.04, 0.96]])
    mu = np.array([-2.0, 0.5])
    phi = 0.3
    if S is None:
        S = np.zeros(T, int)
        for t in range(1, T):
            S[t] = rng.choice(2, p=P[S[t - 1]])
    z = np.zeros(T)
    for t in range(1, T):
        z[t] = phi * z[t - 1] + rng.standard_normal()
    if lam is None:
        lam = 0.6 + 0.4 * rng.random(N)
    f = mu[S] + z
    x = np.outer(f, lam) + 0.6 * rng.standard_normal((T, N))
    x[rng.random((T, N)) < 0.05] = np.nan
    return x, S


def test_fit_recovers_regimes(rng):
    x, S = _two_regime_panel(rng)
    res = fit_ms_dfm(x, n_steps=400)
    # loss decreased and stayed finite
    assert np.isfinite(res.loss_path).all()
    assert res.loss_path[-1] < res.loss_path[0] - 0.1
    # regime classification vs truth (regime 0 = low mean by construction)
    pred0 = np.asarray(res.smoothed_probs[:, 0]) > 0.5
    acc = max((pred0 == (S == 0)).mean(), (pred0 == (S == 1)).mean())
    assert acc > 0.85, acc
    # means ordered and separated
    mu = np.asarray(res.params.mu)
    assert mu[0] < mu[1] and (mu[1] - mu[0]) > 0.4, mu


@pytest.mark.slow
def test_real_panel_recession_probabilities(dataset_real):
    """On the included :Real panel the low-regime smoothed probability
    must be ELEVATED during the Great Recession (2008Q1-2009Q2) relative
    to its full-sample mean — the Chauvet-Piger readout."""
    import numpy as np

    x = np.asarray(dataset_real.bpdata)[:, np.asarray(dataset_real.inclcode) == 1]
    x = x[2:224]
    res = fit_ms_dfm(x, n_steps=500)
    prob = np.asarray(res.smoothed_probs[:, 0])
    # calvec starts 1959Q1 at row 0 of bpdata; window starts at row 2
    # (1959Q3).  2008Q1 = (2008-1959)*4 + 0 = 196 -> index 194 in-window;
    # 2009Q2 inclusive -> 194..199
    gr = prob[194:200].mean()
    assert np.isfinite(res.loglik)
    assert gr > prob.mean() + 0.2, (gr, prob.mean())
    assert gr > 0.5, gr


def test_three_regimes_compile_and_run(rng):
    """n_regimes is a free static: M=3 must compile and produce ordered
    means and a valid transition matrix."""
    x, _ = _two_regime_panel(rng, T=250)
    res = fit_ms_dfm(x, n_regimes=3, n_steps=150, n_restarts=2)
    mu = np.asarray(res.params.mu)
    P = np.asarray(res.params.P)
    assert mu.shape == (3,) and (np.diff(mu) > 0).all()
    assert np.allclose(P.sum(axis=1), 1.0, atol=1e-6) and (P >= 0).all()
    assert np.isfinite(res.loglik)
    assert np.allclose(np.asarray(res.smoothed_probs).sum(axis=1), 1.0, atol=1e-5)


def test_heavy_missingness_stays_finite(rng):
    """40% missing cells incl. fully-missing rows: the collapsed filter
    weights them out exactly; fit must stay finite."""
    x, _ = _two_regime_panel(rng, T=200)
    x[rng.random(x.shape) < 0.4] = np.nan
    x[50] = np.nan  # a fully-missing period
    res = fit_ms_dfm(x, n_steps=150, n_restarts=2)
    assert np.isfinite(res.loglik)
    assert np.isfinite(np.asarray(res.smoothed_probs)).all()


@pytest.mark.slow
def test_monthly_recession_dating():
    """The actual Chauvet (1998) setting: monthly series only, monthly
    recession probabilities — elevated through the Great Recession months."""
    from dynamic_factor_models_tpu.io.cache import cached_monthly_dataset

    ds = cached_monthly_dataset("All")
    cal = np.asarray(ds.calvec)
    keep = (np.asarray(ds.inclcode) == 1) & (~ds.is_quarterly)
    x = np.asarray(ds.data)[:, keep]
    res = fit_ms_dfm(x, n_steps=500)
    prob = np.asarray(res.smoothed_probs[:, 0])
    # monthly dating is sharp: the probability concentrates in the acute
    # phase (Sep-08..Mar-09) rather than the full NBER span
    acute = prob[(cal >= 2008.66) & (cal <= 2009.26)].mean()
    window = prob[(cal >= 2008.0) & (cal <= 2009.5)]
    assert np.isfinite(res.loglik)
    assert acute > 0.5, acute
    assert window.max() > 0.8, window.max()
    assert window.mean() > prob.mean() + 0.2, (window.mean(), prob.mean())


def test_switching_variance_recovery(rng):
    """Kim-Nelson switching volatility: a DGP whose low-mean regime also
    has 4x the innovation variance — the fitted relative sigma2 must be
    materially above 1 for the low regime... with the regime-0 anchor at
    1, the HIGH regime's fitted variance must come out well BELOW the low
    regime's (ratio identified, not the level)."""
    T, N = 400, 8
    P = np.array([[0.92, 0.08], [0.04, 0.96]])
    mu = np.array([-2.0, 0.5])
    phi = 0.3
    sig = np.array([2.0, 1.0])  # innovation SD by regime (low regime wilder)
    S = np.zeros(T, int)
    z = np.zeros(T)
    for t in range(1, T):
        S[t] = rng.choice(2, p=P[S[t - 1]])
        z[t] = phi * z[t - 1] + sig[S[t]] * rng.standard_normal()
    lam = 0.6 + 0.4 * rng.random(N)
    x = np.outer(mu[S] + z, lam) + 0.6 * rng.standard_normal((T, N))
    res = fit_ms_dfm(x, n_steps=500, switching_variance=True)
    s2 = np.asarray(res.params.sigma2)
    # anchor: regime 0 (low mean) has sigma2 = 1; the calm regime's
    # variance must be fitted well below it (true ratio 1/4)
    assert s2[0] == 1.0
    assert s2[1] < 0.6, s2
    # regime classification still works
    pred0 = np.asarray(res.smoothed_probs[:, 0]) > 0.5
    acc = max((pred0 == (S == 0)).mean(), (pred0 == (S == 1)).mean())
    assert acc > 0.85, acc


def test_switching_variance_off_matches_plain(rng):
    """switching_variance=False keeps sigma2 pinned at ones."""
    x, _ = _two_regime_panel(rng, T=200)
    a = fit_ms_dfm(x, n_steps=60, n_restarts=2)
    assert np.allclose(np.asarray(a.params.sigma2), 1.0)


def _dense_kim_loglik(params, x):
    """Independent plain-NumPy Kim (1994) recursion (explicit loops and
    per-pair scalars — no broadcasting) for the scalar-factor MS-DFM with
    regime-dependent innovation variance.  Guards the jax filter's
    vectorized (i, j) algebra."""
    lam = np.asarray(params.lam)
    R = np.asarray(params.R)
    mu = np.asarray(params.mu)
    phi = float(params.phi)
    P = np.asarray(params.P)
    sig2 = np.asarray(params.sigma2)
    M = mu.shape[0]
    T = x.shape[0]
    m = np.zeros(M)
    V = sig2 / max(1.0 - phi**2, 1e-3)
    p = np.full(M, 1.0 / M)
    ll = 0.0
    for t in range(T):
        obs = ~np.isnan(x[t])
        lo, Ro, xo = lam[obs], R[obs], x[t, obs]
        C = float((lo * lo / Ro).sum())
        b = float((lo * xo / Ro).sum())
        ld_R = float(np.log(Ro).sum())
        xRx = float((xo * xo / Ro).sum())
        n = int(obs.sum())
        lik = np.zeros((M, M))
        m_u = np.zeros((M, M))
        P_u = np.zeros((M, M))
        for i in range(M):
            for j in range(M):
                a = phi * m[i]
                Pp = phi**2 * V[i] + sig2[j]
                b_j = b - C * mu[j]
                xRx_j = xRx - 2.0 * mu[j] * b + C * mu[j] ** 2
                Pu = 1.0 / (1.0 / Pp + C)
                rhs = b_j - C * a
                m_u[i, j] = a + Pu * rhs
                P_u[i, j] = Pu
                quad = (xRx_j - 2.0 * a * b_j + C * a**2) - rhs * Pu * rhs
                lik[i, j] = -0.5 * (
                    n * np.log(2 * np.pi)
                    + ld_R
                    + np.log(Pp)
                    - np.log(Pu)
                    + quad
                )
        joint = p[:, None] * P * np.exp(lik - lik.max())
        step = joint.sum()
        ll += np.log(step) + lik.max()
        w = joint / step
        p = w.sum(axis=0)
        m_new = (w * m_u).sum(axis=0) / p
        V = (w * (P_u + (m_u - m_new[None, :]) ** 2)).sum(axis=0) / p
        m = m_new
    return ll


def test_two_regime_filter_matches_dense_reference(rng):
    """The vectorized jax Kim filter equals the looped NumPy reference on
    a two-regime model with and without switching variance — the guard
    for the (i, j) broadcasting algebra."""
    x, _ = _two_regime_panel(rng, T=80)
    x = x[:, :5]
    for sig2 in ([1.0, 1.0], [1.0, 0.4]):
        params = MSDFMParams(
            lam=jnp.asarray(0.8 * np.ones(5)),
            R=jnp.asarray(0.5 + rng.random(5)),
            mu=jnp.asarray([-1.5, 0.4]),
            phi=jnp.asarray(0.6),
            P=jnp.asarray([[0.9, 0.1], [0.05, 0.95]]),
            sigma2=jnp.asarray(sig2),
        )
        xj = jnp.asarray(x)
        ll, *_ = kim_filter(params, xj, mask_of(xj))
        ll_ref = _dense_kim_loglik(params, x)
        assert abs(float(ll) - ll_ref) < 1e-6 * (1 + abs(ll_ref)), (
            sig2,
            float(ll),
            ll_ref,
        )


def test_forecast_ms_properties(rng):
    """Forecast distribution sanity: h=large regime probs converge to the
    chain's stationary distribution; factor mean decays toward the
    stationary regime-mean mixture; variances are positive and growing
    toward the stationary variance."""
    from dynamic_factor_models_tpu.models.msdfm import forecast_ms

    x, _ = _two_regime_panel(rng, T=200)
    res = fit_ms_dfm(x, n_steps=200, n_restarts=2)
    xj = jnp.asarray(x)
    ll, filt, pred, m_f, P_f = kim_filter(res.params, xj, mask_of(xj))
    fc = forecast_ms(res.params, filt, m_f, P_f, horizon=240)
    probs = np.asarray(fc.regime_probs)
    assert probs.shape == (240, 2)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)
    # stationary distribution of the fitted chain
    P = np.asarray(res.params.P)
    evals, evecs = np.linalg.eig(P.T)
    pi = np.real(evecs[:, np.argmax(np.real(evals))])
    pi = pi / pi.sum()
    np.testing.assert_allclose(probs[-1], pi, atol=1e-3)
    # long-horizon factor mean -> stationary mixture mean
    mu = np.asarray(res.params.mu)
    np.testing.assert_allclose(
        float(fc.factor_mean[-1]), float(pi @ mu), atol=1e-2
    )
    var = np.asarray(fc.factor_var)
    assert (var > 0).all()
    assert fc.series_mean.shape == (240, x.shape[1])


def test_opg_standard_errors(rng):
    """OPG/delta-method SEs: finite and positive for free parameters, the
    sigma2 anchor has SE 0, and on a well-identified design the true
    regime means fall within rough 4-SE bands of the estimates."""
    from dynamic_factor_models_tpu.models.msdfm import ms_standard_errors

    x, S = _two_regime_panel(rng)
    res = fit_ms_dfm(x, n_steps=400)
    xstd = (np.asarray(x) - np.asarray(res.means)) / np.asarray(res.stds)
    # default: structural block (mu, phi, P, sigma2), lam/R held fixed
    se = ms_standard_errors(res.params, xstd)
    assert np.isfinite(np.asarray(se.mu)).all() and (np.asarray(se.mu) > 0).all()
    assert float(se.phi) > 0 and np.isfinite(float(se.phi))
    assert np.isfinite(np.asarray(se.P)).all()
    assert np.isnan(np.asarray(se.lam)).all()  # no inference in this mode
    # the sigma2 anchor is structurally fixed: zero standard error
    assert float(se.sigma2[0]) == 0.0
    # the OPG escape hatch stays alive, and cov values are validated
    se_opg = ms_standard_errors(res.params, xstd, cov="opg")
    assert np.isfinite(np.asarray(se_opg.mu)).all()
    with pytest.raises(ValueError, match="cov"):
        ms_standard_errors(res.params, xstd, cov="hac")
    # which="all" is well-posed here (T=400 > d~26) and covers lam too
    se_all = ms_standard_errors(res.params, xstd, which="all")
    assert np.isfinite(np.asarray(se_all.lam)).all()
    assert (np.asarray(se_all.lam) > 0).all()
    # and it must REFUSE a rank-deficient design (T < #params)
    import pytest as _pytest

    with _pytest.raises(ValueError, match="time steps"):
        ms_standard_errors(res.params, xstd[:20], which="all")
    # rough coverage: the standardized-scale true means are the fitted
    # panel's regime means of (mu_true - E mu)/... — use the fitted mu as
    # center and require the SEs to be small relative to the separation
    mu_hat = np.asarray(res.params.mu)
    assert np.asarray(se.mu).max() < 0.5 * (mu_hat[1] - mu_hat[0]), (
        np.asarray(se.mu),
        mu_hat,
    )


@pytest.mark.slow
def test_se_calibration_monte_carlo_fixed_regime_path():
    """Sandwich-SE calibration against Monte-Carlo spread, holding the
    REGIME PATH fixed across replications and redrawing only the Gaussian
    innovations: the default SEs condition on the standardization, and
    with a persistent chain the realized regime mix moves each
    replication's sample means enough to dominate the cross-rep spread of
    mu-hat (a preprocessing channel — propagated by `x_raw=`, see the
    free-path test below).  With the path fixed, the mean reported SE
    must sit within a factor ~2 of the Monte-Carlo SD."""
    from dynamic_factor_models_tpu.models.msdfm import ms_standard_errors

    T, N = 400, 8
    P = np.array([[0.92, 0.08], [0.04, 0.96]])
    path_rng = np.random.default_rng(100)
    S = np.zeros(T, int)
    for t in range(1, T):
        S[t] = path_rng.choice(2, p=P[S[t - 1]])
    lam = 0.6 + 0.4 * path_rng.random(N)

    mus, ses = [], []
    for rep in range(10):
        rng = np.random.default_rng(500 + rep)
        x, _ = _two_regime_panel(rng, T=T, N=N, S=S, lam=lam)
        res = fit_ms_dfm(x, n_steps=300, n_restarts=2)
        xstd = (np.asarray(x) - np.asarray(res.means)) / np.asarray(res.stds)
        se = ms_standard_errors(res.params, xstd)
        mus.append(np.asarray(res.params.mu))
        ses.append(np.asarray(se.mu))
    mus, ses = np.array(mus), np.array(ses)
    sd_mc = mus.std(axis=0, ddof=1)
    se_mean = ses.mean(axis=0)
    ratio = se_mean / np.maximum(sd_mc, 1e-8)
    assert (ratio > 0.5).all() and (ratio < 2.0).all(), (
        sd_mc,
        se_mean,
        ratio,
    )


@pytest.mark.slow
def test_se_calibration_monte_carlo_free_regime_path():
    """Sandwich-SE calibration with the regime path FREE — the production
    setting (round-4 verdict item 4).  Each replication redraws the chain,
    so the realized regime mix moves the per-series sample means/stds the
    panel is standardized with; `x_raw=` propagates that first stage
    through the sandwich (stacked M-estimator: adjusted scores s_t - C u_t
    with a Bartlett long-run meat).  Measured on this design: plain
    conditional ratios [0.95, 0.49] (regime 1 understated 2x), propagated
    [1.53, 0.74] — the mean propagated SE must sit within a factor ~2 of
    the Monte-Carlo SD for BOTH regimes, and must not be smaller than the
    conditional SE (the correction only adds variance)."""
    from dynamic_factor_models_tpu.models.msdfm import ms_standard_errors

    T, N = 400, 8
    lam = 0.6 + 0.4 * np.random.default_rng(100).random(N)

    mus, ses_prop, ses_plain = [], [], []
    for rep in range(10):
        rng = np.random.default_rng(500 + rep)
        x, _ = _two_regime_panel(rng, T=T, N=N, lam=lam)  # free path
        res = fit_ms_dfm(x, n_steps=300, n_restarts=2)
        xstd = (np.asarray(x) - np.asarray(res.means)) / np.asarray(res.stds)
        ses_plain.append(np.asarray(ms_standard_errors(res.params, xstd).mu))
        se = ms_standard_errors(res.params, xstd, x_raw=x)
        mus.append(np.asarray(res.params.mu))
        ses_prop.append(np.asarray(se.mu))
    mus = np.array(mus)
    sd_mc = mus.std(axis=0, ddof=1)
    ratio = np.array(ses_prop).mean(axis=0) / np.maximum(sd_mc, 1e-8)
    assert (ratio > 0.5).all() and (ratio < 2.0).all(), (
        sd_mc,
        np.array(ses_prop).mean(axis=0),
        ratio,
    )
    # the propagated variance dominates the conditional one rep-by-rep
    assert (np.array(ses_prop) >= np.array(ses_plain) * 0.99).all()


def test_se_propagation_validation():
    """x_raw plumbing: shape mismatch and a panel that does not
    standardize to x are both rejected loudly."""
    from dynamic_factor_models_tpu.models.msdfm import ms_standard_errors

    rng = np.random.default_rng(3)
    x, _ = _two_regime_panel(rng, T=200, N=6)
    res = fit_ms_dfm(x, n_steps=250, n_restarts=2)
    xstd = (np.asarray(x) - np.asarray(res.means)) / np.asarray(res.stds)
    with pytest.raises(ValueError, match="shape"):
        ms_standard_errors(res.params, xstd, x_raw=x[:60])
    with pytest.raises(ValueError, match="standardize"):
        # a genuinely different panel (rows reversed) — note a per-series
        # AFFINE transform would standardize to the same xstd and is
        # correctly accepted: the propagated SEs are invariant to it
        ms_standard_errors(res.params, xstd, x_raw=x[::-1])
    # propagated SEs on the fitted panel: finite and positive
    se_p = ms_standard_errors(res.params, xstd, x_raw=x)
    assert np.isfinite(np.asarray(se_p.mu)).all()
    assert (np.asarray(se_p.mu) > 0).all()
