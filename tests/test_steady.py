"""Steady-state Kalman fast path (models/steady.py + ssm method="steady").

Three claims are pinned here:

1. the structure-preserving doubling solver lands on the same DARE fixed
   point as `scipy.linalg.solve_discrete_are` (f64, 1e-8) on random
   stable systems, and a warm start from a nearby solution needs no more
   doublings than a cold solve;
2. the steady filter/smoother/E-step — exact covariance head of length
   t*, constant-gain factorization-free tail, closed-form tail covariance
   moments — matches `method="sequential"` to 1e-10 in f64 on
   complete-tail panels with ragged missing heads, through a full
   `estimate_dfm_em` run (warm-started doubling in the EM carry included);
3. the mask gate is sound: interior missingness falls back to the exact
   sequential path bit-for-bit, and the periodic (cyclostationary) gain
   set reproduces the mixed-frequency filter's late-time covariance cycle.
"""

import numpy as np
import pytest
import scipy.linalg

import jax
import jax.numpy as jnp

from dynamic_factor_models_tpu.models.ssm import (
    SSMParams,
    SteadyEMState,
    _companion,
    _steady_plan,
    compute_panel_stats,
    em_step_stats,
    em_step_steady,
    estimate_dfm_em,
    kalman_filter,
    kalman_smoother,
)
from dynamic_factor_models_tpu.models.steady import (
    dare_doubling,
    linear_recursion,
    periodic_dare,
    steady_state,
)


def _random_stable_system(rng, k, n, rho=0.7):
    """(Tm, Qs, H, R) with spectral radius rho: the generic dense test
    system for the DARE solver (the DFM companion form is a special case)."""
    Tm = rng.standard_normal((k, k))
    Tm *= rho / max(abs(np.linalg.eigvals(Tm)))
    G = rng.standard_normal((k, k))
    Qs = G @ G.T / k + 0.1 * np.eye(k)
    H = rng.standard_normal((n, k))
    R = 0.5 + rng.random(n)
    return Tm, Qs, H, R


def _dgp(seed=3, T=224, N=60, r=3, p=2, n_ragged=20):
    """Complete-tail panel with ragged missing heads — the regime the
    steady gate admits — plus a deliberately rough parameter start."""
    rng = np.random.default_rng(seed)
    A1 = 0.6 * np.eye(r) + 0.05 * rng.standard_normal((r, r))
    lam = rng.standard_normal((N, r))
    f = np.zeros((T + 10, r))
    for t in range(1, T + 10):
        f[t] = A1 @ f[t - 1] + rng.standard_normal(r) * 0.5
    x = f[10:] @ lam.T + rng.standard_normal((T, N)) * 0.8
    mask = np.ones((T, N), bool)
    for i in range(n_ragged):
        mask[: rng.integers(5, 30), i] = False
    xz = jnp.asarray(np.where(mask, x, 0.0))
    m = jnp.asarray(mask)
    params = SSMParams(
        lam=jnp.asarray(lam + 0.3 * rng.standard_normal((N, r))),
        R=jnp.ones(N, xz.dtype),
        A=jnp.concatenate(
            [0.5 * jnp.eye(r, dtype=xz.dtype)[None], jnp.zeros((p - 1, r, r))]
        ),
        Q=jnp.eye(r, dtype=xz.dtype),
    )
    return params, xz, m, x


@pytest.fixture(scope="module")
def dgp():
    return _dgp()


# ---------------------------------------------------------------------------
# DARE doubling vs scipy
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed,k,n", [(0, 6, 10), (1, 8, 5), (2, 4, 12)])
def test_dare_doubling_matches_scipy(seed, k, n):
    rng = np.random.default_rng(seed)
    Tm, Qs, H, R = _random_stable_system(rng, k, n)
    C = (H.T / R) @ H
    X, iters, converged = dare_doubling(
        jnp.asarray(Tm), jnp.asarray(C), jnp.asarray(Qs)
    )
    assert bool(converged)
    # filter-form DARE == scipy's control-form DARE with (A', H', Q, R)
    X_ref = scipy.linalg.solve_discrete_are(Tm.T, H.T, Qs, np.diag(R))
    np.testing.assert_allclose(np.asarray(X), X_ref, rtol=1e-8, atol=1e-8)


def test_dare_warm_start_needs_no_more_doublings():
    rng = np.random.default_rng(4)
    Tm, Qs, H, R = _random_stable_system(rng, 6, 8)
    C = jnp.asarray((H.T / R) @ H)
    Tm, Qs = jnp.asarray(Tm), jnp.asarray(Qs)
    X, cold_iters, _ = dare_doubling(Tm, C, Qs)
    # perturb the fixed point slightly — the EM-carry situation, where the
    # previous iteration's Pp is near the new parameters' fixed point
    X0 = X + 1e-3 * jnp.eye(X.shape[0])
    Xw, warm_iters, converged = dare_doubling(Tm, C, Qs, X0=X0)
    assert bool(converged)
    assert int(warm_iters) <= int(cold_iters)
    np.testing.assert_allclose(np.asarray(Xw), np.asarray(X), atol=1e-10)


def test_steady_state_fixed_point_identities(dgp):
    params, xz, m, _ = dgp
    r = params.r
    Tm, Qs = _companion(params._replace(Q=params.Q))
    C = jnp.asarray((params.lam.T * (1.0 / params.R)) @ params.lam)
    st = steady_state(Tm, C, Qs, q=r)
    assert bool(st.converged)
    k = Tm.shape[0]
    Pp, Pu = np.asarray(st.Pp), np.asarray(st.Pu)
    Cf = np.zeros((k, k))
    Cf[:r, :r] = np.asarray(C)
    # update identity Pu = (Pp^-1 + C)^-1 and predict identity
    np.testing.assert_allclose(
        Pu, np.linalg.inv(np.linalg.inv(Pp) + Cf), atol=1e-12
    )
    np.testing.assert_allclose(
        Pp, np.asarray(Tm) @ Pu @ np.asarray(Tm).T + np.asarray(Qs), atol=1e-12
    )
    # smoothed covariance solves its Stein equation Ps = Pu + J(Ps - Pp)J'
    J, Ps = np.asarray(st.J), np.asarray(st.Ps)
    np.testing.assert_allclose(Ps, Pu + J @ (Ps - Pp) @ J.T, atol=1e-12)


def test_linear_recursion_blocked_matches_scan():
    rng = np.random.default_rng(5)
    k, T = 8, 173  # deliberately not a multiple of the block size
    M = rng.standard_normal((k, k))
    M *= 0.8 / max(abs(np.linalg.eigvals(M)))
    g = jnp.asarray(rng.standard_normal((T, k)))
    s0 = jnp.asarray(rng.standard_normal(k))
    M = jnp.asarray(M)
    ref = linear_recursion(M, g, s0, block=0)
    for block in (8, 32, 256):
        out = linear_recursion(M, g, s0, block=block)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-12)


# ---------------------------------------------------------------------------
# steady filter / smoother / EM parity vs sequential
# ---------------------------------------------------------------------------


def test_steady_filter_matches_sequential(dgp):
    params, xz, m, x_raw = dgp
    xnan = jnp.where(m, jnp.asarray(x_raw), jnp.nan)
    ref = kalman_filter(params, xnan, method="sequential")
    out = kalman_filter(params, xnan, method="steady")
    assert _steady_plan(params, np.asarray(m)) is not None  # fast path taken
    np.testing.assert_allclose(
        float(out.loglik), float(ref.loglik), rtol=1e-10
    )
    np.testing.assert_allclose(
        np.asarray(out.means), np.asarray(ref.means), atol=1e-10
    )
    np.testing.assert_allclose(
        np.asarray(out.covs), np.asarray(ref.covs), atol=1e-10
    )


def test_steady_smoother_matches_sequential(dgp):
    params, xz, m, x_raw = dgp
    xnan = jnp.where(m, jnp.asarray(x_raw), jnp.nan)
    means_ref, covs_ref, ll_ref = kalman_smoother(params, xnan, method="sequential")
    means, covs, ll = kalman_smoother(params, xnan, method="steady")
    np.testing.assert_allclose(float(ll), float(ll_ref), rtol=1e-10)
    np.testing.assert_allclose(
        np.asarray(means), np.asarray(means_ref), atol=1e-10
    )
    np.testing.assert_allclose(
        np.asarray(covs), np.asarray(covs_ref), atol=1e-10
    )


@pytest.mark.parametrize("block", [0, 16])
def test_em_step_steady_matches_sequential(dgp, block):
    params, xz, m, _ = dgp
    stats = compute_panel_stats(xz, m)
    plan = _steady_plan(params, np.asarray(m))
    assert plan is not None
    t_star, _, _ = plan
    new_ref, ll_ref = em_step_stats(params, xz, m, stats)
    out, ll = em_step_steady(params, xz, m, stats, t_star, block=block)
    assert isinstance(out, SteadyEMState)
    np.testing.assert_allclose(float(ll), float(ll_ref), rtol=1e-10)
    for a, b in zip(out.params, new_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-10)
    # the carry exposes the warm-startable fixed point + solver effort
    assert int(out.riccati_iters) > 0
    # second (warm) step: Pp carried from the first solve
    out2, _ = em_step_steady(out, xz, m, stats, t_star, block=block)
    ref2, _ = em_step_stats(new_ref, xz, m, stats)
    for a, b in zip(out2.params, ref2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-10)


def test_estimate_dfm_em_steady_end_to_end(dgp, monkeypatch, tmp_path):
    from dynamic_factor_models_tpu.models.dfm import DFMConfig

    _, xz, m, x_raw = dgp
    xm = np.where(np.asarray(m), x_raw, np.nan)
    incl = np.ones(xm.shape[1], int)
    cfg = DFMConfig(nfac_u=3, n_factorlag=2)
    tele = tmp_path / "steady.jsonl"
    monkeypatch.setenv("DFM_TELEMETRY", str(tele))
    T = xm.shape[0]
    res_seq = estimate_dfm_em(xm, incl, 0, T - 1, cfg, max_em_iter=12, tol=0.0)
    res_st = estimate_dfm_em(
        xm, incl, 0, T - 1, cfg, max_em_iter=12, tol=0.0, method="steady"
    )
    np.testing.assert_allclose(
        res_st.loglik_path, res_seq.loglik_path, rtol=1e-10
    )
    np.testing.assert_allclose(
        np.asarray(res_st.factors), np.asarray(res_seq.factors), atol=1e-10
    )
    np.testing.assert_allclose(
        np.asarray(res_st.factor_covs),
        np.asarray(res_seq.factor_covs),
        atol=1e-10,
    )
    # the steady result carries plain SSMParams (the carry is unwrapped)
    assert isinstance(res_st.params, SSMParams)
    # telemetry: t_star / steady_frac / riccati_iters land in the RunRecord
    import json

    recs = [json.loads(l) for l in tele.read_text().splitlines()]
    st_recs = [
        r for r in recs
        if r.get("entry") == "estimate_dfm_em"
        and r.get("config", {}).get("method") == "steady"
    ]
    assert st_recs, "no steady RunRecord emitted"
    rec = st_recs[-1]
    assert rec["t_star"] >= 2
    assert 0.0 < rec["steady_frac"] < 1.0
    assert rec["riccati_iters"] > 0


def test_steady_gate_falls_back_on_interior_missing(dgp):
    params, xz, m, x_raw = dgp
    rng = np.random.default_rng(9)
    mask = np.asarray(m).copy()
    mask[rng.random(mask.shape) < 0.05] = False  # interior holes
    assert _steady_plan(params, mask) is None
    xnan = jnp.where(jnp.asarray(mask), jnp.asarray(x_raw), jnp.nan)
    # the public entry points silently take the exact sequential path
    ref = kalman_filter(params, xnan, method="sequential")
    out = kalman_filter(params, xnan, method="steady")
    assert float(out.loglik) == float(ref.loglik)
    means_ref, _, _ = kalman_smoother(params, xnan, method="sequential")
    means, _, _ = kalman_smoother(params, xnan, method="steady")
    np.testing.assert_array_equal(np.asarray(means), np.asarray(means_ref))


def test_steady_rejects_accel(dgp):
    from dynamic_factor_models_tpu.models.dfm import DFMConfig

    _, xz, m, x_raw = dgp
    xm = np.where(np.asarray(m), x_raw, np.nan)
    with pytest.raises(ValueError, match="steady"):
        estimate_dfm_em(
            xm,
            np.ones(xm.shape[1], int),
            0,
            xm.shape[0] - 1,
            DFMConfig(nfac_u=3, n_factorlag=2),
            method="steady",
            accel="squarem",
        )


# ---------------------------------------------------------------------------
# periodic (mixed-frequency) gain cycle
# ---------------------------------------------------------------------------


def test_periodic_dare_matches_mixed_freq_filter_cycle():
    from dynamic_factor_models_tpu.models.mixed_freq import (
        _MM_WEIGHTS,
        MixedFreqParams,
        _filter_mf,
        steady_gains,
    )

    rng = np.random.default_rng(11)
    T, N, r, p = 150, 24, 2, 5
    is_q = np.zeros(N, bool)
    is_q[16:] = True
    agg = np.zeros((N, 5))
    agg[~is_q, 0] = 1.0
    agg[is_q] = _MM_WEIGHTS
    params = MixedFreqParams(
        lam=jnp.asarray(rng.standard_normal((N, r))),
        R=jnp.ones(N),
        A=jnp.concatenate(
            [0.5 * jnp.eye(r)[None], jnp.zeros((p - 1, r, r))]
        ),
        Q=jnp.eye(r),
        agg=jnp.asarray(agg),
    )
    st = steady_gains(params)  # default pattern: quarter-end at t % 3 == 2
    assert bool(st.converged)
    mask = np.ones((T, N), bool)
    for t in range(T):
        if t % 3 != 2:
            mask[t, is_q] = False
    x = rng.standard_normal((T, N)) * mask
    _, covs, _, pcovs, _ = _filter_mf(
        params, jnp.asarray(x), jnp.asarray(mask)
    )
    for j in range(3):
        ts = [t for t in range(T - 12, T) if t % 3 == j]
        for t in ts:
            np.testing.assert_allclose(
                np.asarray(covs[t]), np.asarray(st.Pu[j]), atol=1e-10
            )
            np.testing.assert_allclose(
                np.asarray(pcovs[t]), np.asarray(st.Pp[j]), atol=1e-10
            )


def test_periodic_dare_constant_pattern_reduces_to_dare():
    rng = np.random.default_rng(12)
    Tm, Qs, H, R = _random_stable_system(rng, 6, 9)
    C = (H.T / R) @ H
    Tm, Qs, C = jnp.asarray(Tm), jnp.asarray(Qs), jnp.asarray(C)
    st = steady_state(Tm, C, Qs)
    per = periodic_dare(Tm, jnp.stack([C, C, C]), Qs)
    for j in range(3):
        np.testing.assert_allclose(
            np.asarray(per.Pp[j]), np.asarray(st.Pp), atol=1e-10
        )
        np.testing.assert_allclose(
            np.asarray(per.Pu[j]), np.asarray(st.Pu), atol=1e-10
        )


# ---------------------------------------------------------------------------
# emloop satellite: traced stop_at under collect_path
# ---------------------------------------------------------------------------


def test_collect_path_traced_stop_at_raises_clear_error(dgp):
    from dynamic_factor_models_tpu.models.emloop import run_em_loop

    params, xz, m, _ = dgp
    stats = compute_panel_stats(xz, m.astype(xz.dtype))

    @jax.jit
    def bad(bound):
        out, _, _, _ = run_em_loop(
            em_step_stats,
            params,
            (xz, m.astype(xz.dtype), stats),
            1e-6,
            4,
            collect_path=True,
            stop_at=bound,
        )
        return out

    with pytest.raises(ValueError, match="collect_path"):
        bad(jnp.asarray(2, jnp.int32))
