import os

# Tests run on a virtual 8-device CPU mesh (SURVEY.md section 4): multi-chip
# sharding logic is exercised without TPU hardware, and float64 is enabled for
# golden-value parity with the reference outputs.
os.environ["JAX_PLATFORMS"] = "cpu"  # env presets axon (TPU); tests run CPU
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

# The axon TPU plugin (sitecustomize) force-sets jax_platforms="axon,cpu" at
# registration, so the env var alone is not enough — override at config level.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from dynamic_factor_models_tpu.io.cache import cached_dataset  # noqa: E402
from dynamic_factor_models_tpu.utils.compile import (  # noqa: E402
    configure_compilation_cache,
)

# Persist compiled executables across test runs (build/jax_cache, gitignored).
# The suite compiles hundreds of XLA CPU programs; with a warm cache most are
# deserialized from disk instead of recompiled, and the module-boundary
# jax.clear_caches() below drops only the in-process caches — reloads still
# hit the disk cache.  DFM_COMPILE_CACHE=0 disables (compile.py kill-switch).
configure_compilation_cache()


def pytest_collection_modifyitems(config, items):
    """`multidevice` tests need the virtual 8-device CPU platform.  The
    XLA flag is set above, in-process, before the first jax import — but
    if this conftest ran too late (jax imported by a plugin first) or the
    flag was stripped, device_count() comes back 1 and every sharding
    test would fail confusingly.  Skip with a diagnostic instead."""
    if jax.device_count() >= 8:
        return
    skip = pytest.mark.skip(
        reason=(
            f"multidevice tests need >= 8 devices, got {jax.device_count()} "
            "(XLA_FLAGS=--xla_force_host_platform_device_count=8 must be set "
            "before jax initializes)"
        )
    )
    for item in items:
        if "multidevice" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Bound the per-process live-JIT footprint: the full suite compiles
    hundreds of XLA CPU programs in one process, and past a cumulative
    volume the LLVM JIT segfaults inside backend_compile_and_load
    (observed at different, individually-innocent programs — order-
    dependent, neither suite half reproduces alone).  Dropping the
    compilation caches at module boundaries keeps the live-code volume
    bounded at the cost of a few repeated compilations."""
    yield
    jax.clear_caches()


@pytest.fixture(scope="session")
def dataset_real():
    return cached_dataset("Real")


@pytest.fixture(scope="session")
def dataset_all():
    return cached_dataset("All")


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
