"""Wide-bucket serving refits dispatch the collapse-first kernel.

`serving.batch.refit_batch` with the default `step=None` resolves each
bucket's step from the transform stack: a bucket whose padded N crosses
`ssm.LARGE_N_THRESHOLD` runs `emcore.em_step_collapsed` instead of
`em_step_stats`.  Pinned claims:

1. the auto-dispatched wide bucket matches the forced dense-step run at
   1e-10 (params, loglik, iteration counts) — the collapse changes the
   schedule, not the numbers;
2. an explicit `step=` suppresses the dispatch (the two forced runs are
   bit-identical), so callers pinning a step keep exactly that step;
3. narrow buckets are unaffected: below the threshold the default path
   still dispatches `em_step_stats`.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from dynamic_factor_models_tpu.models import ssm as _ssm
from dynamic_factor_models_tpu.serving.batch import (
    RefitRequest,
    refit_batch,
)
from dynamic_factor_models_tpu.utils.compile import bucket_shape

pytestmark = [pytest.mark.serving, pytest.mark.large_n]


def _params(rng, N, r=2, a=0.5):
    lam = jnp.asarray(rng.standard_normal((N, r)))
    A = jnp.zeros((1, r, r)).at[0].set(a * jnp.eye(r))
    return _ssm.SSMParams(lam, jnp.ones(N), A, jnp.eye(r))


def _request(rng, tid, T, N, r=2):
    params = _params(rng, N, r)
    f = rng.standard_normal((T, r)) * 0.5
    x = f @ np.asarray(params.lam).T + 0.5 * rng.standard_normal((T, N))
    mask = np.ones((T, N), bool)
    mask[: rng.integers(1, 4), 0] = False
    return RefitRequest(tid, jnp.asarray(x), jnp.asarray(mask), params)


def test_wide_bucket_crosses_threshold():
    # the fixture regime: raw N=520 pads past LARGE_N_THRESHOLD=512
    assert _ssm.LARGE_N_THRESHOLD == 512
    assert bucket_shape(60, 520)[1] > _ssm.LARGE_N_THRESHOLD


def test_wide_bucket_auto_dispatch_matches_forced_dense():
    rng = np.random.default_rng(7)
    reqs = [_request(rng, f"t{i}", T=60, N=520) for i in range(2)]
    auto = refit_batch(reqs, max_em_iter=5)
    forced = refit_batch(reqs, max_em_iter=5, step=_ssm.em_step_stats)
    for a, f in zip(auto, forced):
        assert a.tenant_id == f.tenant_id
        assert a.n_iter == f.n_iter and a.converged == f.converged
        assert a.health == f.health == 0
        assert abs(a.loglik - f.loglik) <= 1e-10 * (1 + abs(f.loglik))
        for pa, pf in zip(a.params, f.params):
            np.testing.assert_allclose(pa, pf, atol=1e-10)


def test_narrow_bucket_unchanged_by_auto_dispatch():
    rng = np.random.default_rng(8)
    reqs = [_request(rng, f"s{i}", T=40, N=12) for i in range(2)]
    assert bucket_shape(40, 12)[1] <= _ssm.LARGE_N_THRESHOLD
    auto = refit_batch(reqs, max_em_iter=5)
    forced = refit_batch(reqs, max_em_iter=5, step=_ssm.em_step_stats)
    for a, f in zip(auto, forced):
        assert a.n_iter == f.n_iter
        np.testing.assert_allclose(a.params.lam, f.params.lam, atol=0)
