"""Bayesian DFM (models/bayes.py): simulation smoother, Gibbs posterior,
and posterior IRFs on synthetic ground truth."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.bayes import (
    estimate_dfm_bayes,
    posterior_irfs,
    rhat,
    simulation_smoother,
)
from dynamic_factor_models_tpu.models.dfm import DFMConfig
from dynamic_factor_models_tpu.models.ssm import SSMParams, kalman_smoother


def _synthetic(T=120, N=12, r=1, rho=0.7, noise=0.5, miss=0.08, seed=0):
    rng = np.random.default_rng(seed)
    f = np.zeros((T, r))
    for t in range(1, T):
        f[t] = rho * f[t - 1] + rng.standard_normal(r)
    lam = rng.standard_normal((N, r))
    x = f @ lam.T + noise * rng.standard_normal((T, N))
    m = rng.random((T, N)) < miss
    m[:, : N // 2] = False  # keep a balanced block for the ALS/PCA init
    x[m] = np.nan
    return x, f, lam


@pytest.fixture(scope="module")
def posterior():
    x, f, lam = _synthetic()
    res = estimate_dfm_bayes(
        jnp.asarray(x), np.ones(x.shape[1], np.int64), 0, x.shape[0] - 1,
        DFMConfig(nfac_u=1, n_factorlag=1, tol=1e-6, max_iter=200),
        n_keep=100, n_burn=100, n_chains=2, seed=0,
    )
    return x, f, lam, res


class TestGibbs:
    def test_recovers_factor_path(self, posterior):
        x, f, lam, res = posterior
        assert res.factor_draws.shape == (2, 100, 120, 1)
        fm = np.asarray(res.factor_draws).mean(axis=(0, 1))[:, 0]
        assert abs(np.corrcoef(fm, f[:, 0])[0, 1]) > 0.9

    def test_recovers_loadings_and_dynamics(self, posterior):
        x, f, lam, res = posterior
        lm = np.asarray(res.lam_draws).mean(axis=(0, 1))[:, 0]
        # standardized units: compare up to scale via correlation
        assert abs(np.corrcoef(lm, lam[:, 0])[0, 1]) > 0.9
        a = float(np.asarray(res.a_draws).mean())
        assert 0.4 < a < 0.95  # truth 0.7 in standardized units
        assert (np.asarray(res.r_draws) > 0).all()
        # posterior Q draws are PD
        assert (np.asarray(res.q_draws)[..., 0, 0] > 0).all()

    def test_chains_mix(self, posterior):
        *_, res = posterior
        assert res.rhat_loglik < 1.2
        assert res.loglik_path.shape == (2, 200)
        assert np.isfinite(res.loglik_path).all()
        # chains started from the same ALS init stay in the same posterior
        # mode: post-burn means agree within the within-chain spread
        post = res.loglik_path[:, 100:]
        gap = abs(post[0].mean() - post[1].mean())
        assert gap < 4.0 * post.std()

    def test_posterior_irfs(self, posterior):
        *_, res = posterior
        qs, draws = posterior_irfs(res, horizon=8)
        assert qs.shape == (5, 1, 8, 1)
        assert draws.shape == (200, 1, 8, 1)
        assert np.isfinite(np.asarray(qs)).all()
        # monotone quantiles
        assert (np.diff(np.asarray(qs), axis=0) >= -1e-12).all()

    def test_posterior_series_irfs(self, posterior):
        from dynamic_factor_models_tpu.models.bayes import posterior_series_irfs

        x, f, lam, res = posterior
        N = x.shape[1]
        out = posterior_series_irfs(res, horizon=8)
        mean, qs = out.mean, out.quantiles
        assert mean.shape == (N, 8, 1)
        assert qs.shape == (5, N, 8, 1)
        assert out.draws.shape == (200, N, 8, 1)
        assert np.isfinite(np.asarray(qs)).all()
        assert (np.diff(np.asarray(qs), axis=0) >= -1e-12).all()
        # the posterior mean sits inside its own 5-95% band
        inside = (np.asarray(mean) >= np.asarray(qs[0])) & (
            np.asarray(mean) <= np.asarray(qs[-1])
        )
        assert inside.mean() > 0.9
        # original units: the impact responses are proportional to the true
        # loadings across series (factor scale is a common constant)
        impact = np.asarray(mean)[:, 0, 0]
        assert abs(np.corrcoef(impact, lam[:, 0])[0, 1]) > 0.9
        # out-of-range indices raise instead of silently clamping
        with pytest.raises(IndexError, match="out of range"):
            posterior_series_irfs(res, horizon=8, series_idx=[N])
        # subset selection slices the full result
        sub = posterior_series_irfs(res, horizon=8, series_idx=[2, 5])
        np.testing.assert_allclose(
            np.asarray(sub.mean), np.asarray(mean)[[2, 5]], rtol=1e-10
        )
        np.testing.assert_allclose(
            np.asarray(sub.quantiles), np.asarray(qs)[:, [2, 5]], rtol=1e-10
        )


@pytest.mark.slow
class TestSimulationSmoother:
    def test_draws_center_on_smoother_mean(self):
        """Average of many posterior factor draws ~= RTS smoothed mean."""
        x, f, lam = _synthetic(T=80, N=8, seed=1)
        params = SSMParams(
            lam=jnp.asarray(lam),
            R=0.25 * jnp.ones(x.shape[1]),
            A=0.7 * jnp.eye(1)[None],
            Q=jnp.eye(1),
        )
        draws = np.stack(
            [np.asarray(simulation_smoother(params, jnp.asarray(x), seed=s)[0])
             for s in range(60)]
        )
        sm_means, sm_covs, _ = kalman_smoother(params, jnp.asarray(x))
        mean_draw = draws.mean(axis=0)[:, 0]
        sm = np.asarray(sm_means)[:, 0]
        sd = np.sqrt(np.asarray(sm_covs)[:, 0, 0])
        # Monte-Carlo error of 60 draws: within ~4 posterior sds / sqrt(60)
        assert np.abs(mean_draw - sm).max() < 4.0 * sd.max() / np.sqrt(60) + 0.05
        # draw dispersion matches the smoother variance scale
        ratio = draws.std(axis=0)[:, 0].mean() / sd.mean()
        assert 0.7 < ratio < 1.3

    def test_exact_for_higher_lag_order(self):
        """Draw mean matches the RTS smoothed mean for p=2 — a backward pass
        conditioning only on f_{t+1} is biased here (up to ~0.2 posterior
        sd); the Durbin-Koopman construction is exact for any p."""
        rng = np.random.default_rng(3)
        T, N = 80, 8
        a1, a2 = 0.5, 0.3
        f = np.zeros(T)
        for t in range(2, T):
            f[t] = a1 * f[t - 1] + a2 * f[t - 2] + rng.standard_normal()
        lam = rng.standard_normal((N, 1))
        x = f[:, None] @ lam.T + 0.5 * rng.standard_normal((T, N))
        params = SSMParams(
            lam=jnp.asarray(lam),
            R=0.25 * jnp.ones(N),
            A=jnp.asarray(np.array([[[a1]], [[a2]]])),
            Q=jnp.eye(1),
        )
        n_draws = 120
        draws = np.stack(
            [np.asarray(simulation_smoother(params, jnp.asarray(x), seed=s)[0])
             for s in range(n_draws)]
        )[:, :, 0]
        sm_means, sm_covs, _ = kalman_smoother(params, jnp.asarray(x))
        sm = np.asarray(sm_means)[:, 0]
        sd = np.sqrt(np.asarray(sm_covs)[:, 0, 0])
        # MC error of the mean is sd/sqrt(n); allow 4x + slack
        tol = 4.0 * sd / np.sqrt(n_draws) + 0.02
        assert (np.abs(draws.mean(axis=0) - sm) < tol).all()

    def test_rhat_sane(self):
        rng = np.random.default_rng(2)
        same = rng.standard_normal((4, 500))
        assert rhat(same) < 1.05
        shifted = same + np.arange(4)[:, None] * 5.0
        assert rhat(shifted) > 2.0


class TestPosteriorForecast:
    @pytest.mark.slow
    def test_predictive_bands_cover_future(self):
        """Fit on the first part of a synthetic sample, forecast the rest:
        the 5-95% predictive band should cover ~90% of realized values."""
        from dynamic_factor_models_tpu.models.bayes import posterior_forecast

        rng = np.random.default_rng(10)
        T, N, h = 160, 10, 8
        f = np.zeros((T + h, 1))
        for t in range(1, T + h):
            f[t] = 0.7 * f[t - 1] + rng.standard_normal(1)
        lam = rng.standard_normal((N, 1))
        x_all = f @ lam.T + 0.5 * rng.standard_normal((T + h, N))
        x_fit = x_all[:T]

        res = estimate_dfm_bayes(
            jnp.asarray(x_fit), np.ones(N, np.int64), 0, T - 1,
            DFMConfig(nfac_u=1, n_factorlag=1, tol=1e-6, max_iter=200),
            n_keep=60, n_burn=60, n_chains=2, seed=0,
        )
        # raw panel in, original units out: standardization is internal
        fc = posterior_forecast(
            res, jnp.asarray(x_fit), np.ones(N, np.int64), 0, T - 1,
            horizon=h, seed=1,
        )
        assert fc.draws.shape == (120, h, N)
        assert np.isfinite(np.asarray(fc.draws)).all()
        lo, hi = fc.quantiles[0], fc.quantiles[-1]
        actual = x_all[T:]  # original units
        cover = ((actual >= lo) & (actual <= hi)).mean()
        # nominal 0.90, but the common factor path correlates all series:
        # only ~h effectively independent events, so the sampling spread of
        # `cover` is wide — bound loosely
        assert 0.70 < cover <= 1.0
        # monotone quantiles and a sane mean (original units)
        assert (np.diff(fc.quantiles, axis=0) >= -1e-9).all()
        assert np.abs(np.asarray(fc.mean)).max() < 5.0 * np.nanstd(x_fit)

    def test_horizon_validation(self, posterior):
        from dynamic_factor_models_tpu.models.bayes import posterior_forecast

        x, *_, res = posterior
        ones = np.ones(x.shape[1], np.int64)
        with pytest.raises(ValueError, match="horizon"):
            posterior_forecast(
                res, jnp.asarray(x), ones, 0, x.shape[0] - 1, horizon=0
            )
        with pytest.raises(ValueError, match="included series"):
            posterior_forecast(
                res, jnp.asarray(x[:, :5]), ones[:5], 0, x.shape[0] - 1,
                horizon=2,
            )


@pytest.mark.slow
class TestModelComparison:
    def test_dic_selects_true_factor_count(self):
        """True r=2 panel: DIC should prefer r=2 over r=1 (underfit) and
        not do worse than r=3 by much (overfit penalized via p_D)."""
        from dynamic_factor_models_tpu.models.bayes import select_nfac_bayes

        rng = np.random.default_rng(11)
        T, N, r_true = 150, 14, 2
        f = np.zeros((T, r_true))
        for t in range(1, T):
            f[t] = 0.6 * f[t - 1] + rng.standard_normal(r_true)
        lam = rng.standard_normal((N, r_true))
        x = f @ lam.T + 0.4 * rng.standard_normal((T, N))

        comp = select_nfac_bayes(
            jnp.asarray(x), np.ones(N, np.int64), 0, T - 1, nfacs=(1, 2, 3),
            config=DFMConfig(n_factorlag=1, tol=1e-6, max_iter=200),
            n_keep=60, n_burn=60, n_chains=2, seed=0,
        )
        assert comp.dic.shape == (3,)
        assert np.isfinite(comp.dic).all()
        # r=2 clearly beats the underfit r=1
        assert comp.dic[1] < comp.dic[0]
        assert comp.best_nfac in (2, 3)
        # effective parameters grow with r
        assert comp.p_d[2] > comp.p_d[0]


@pytest.mark.slow
def test_chain_mesh_sharding():
    """Chains shard over a 1-axis mesh (any axis name) and match shapes."""
    from jax.sharding import Mesh

    x, *_ = _synthetic(T=60, N=8)
    devs = np.array(jax.devices()[:2])
    mesh = Mesh(devs, ("rep",))  # make_mesh's default axis name
    res = estimate_dfm_bayes(
        jnp.asarray(x), np.ones(8, np.int64), 0, 59,
        DFMConfig(nfac_u=1, n_factorlag=1, tol=1e-6, max_iter=100),
        n_keep=10, n_burn=10, n_chains=2, seed=0, mesh=mesh,
    )
    assert res.factor_draws.shape == (2, 10, 60, 1)
    assert np.isfinite(np.asarray(res.factor_draws)).all()
    assert np.isfinite(res.loglik_path).all()


def test_mniw_q_marginal_matches_analytic():
    """The collapsed (Q, A) | f draw must have the analytically-known Q
    marginal.  For r=1, p=1, flat prior on the AR coefficient and
    IW(nu0, s0) prior on Q, integrating the coefficient out gives
    Q | f ~ InvGamma((nu0 + (T-1) - 1)/2, (s0 + ssr_ols)/2) — the matrix
    n - k correction (-rp in the IW degrees of freedom).  Pinned against
    scipy quantiles; without the -rp the 4000-draw median is biased low
    by ~rp/(T-p) (~11% here vs the 5% tolerance) and this test fails."""
    from scipy import stats

    from dynamic_factor_models_tpu.models.bayes import _draw_var_mniw

    rng = np.random.default_rng(11)
    # small T and p=4 make the correction bite (~10% of the df): the
    # uncorrected draw fails the median check here
    T, p, q_df_extra, q_scale = 40, 4, 0.02, 0.01
    f = np.zeros((T, 1))
    for t in range(1, T):
        f[t] = 0.6 * f[t - 1] + rng.standard_normal()
    fj = jnp.asarray(f)

    keys = jax.random.split(jax.random.PRNGKey(0), 4000)
    draw = jax.jit(jax.vmap(lambda k: _draw_var_mniw(k, fj, p, q_df_extra, q_scale)))
    A_d, Q_d = draw(keys)
    q_draws = np.asarray(Q_d)[:, 0, 0]

    Z = np.column_stack([f[p - 1 - i : T - 1 - i, 0] for i in range(p)])
    y = f[p:, 0]
    zz = Z.T @ Z
    ahat = np.linalg.solve(zz, Z.T @ y)
    ssr = ((y - Z @ ahat) ** 2).sum()
    nu0 = 1 + 1 + q_df_extra  # r + 1 + extra
    shape = (nu0 + (T - p) - p) / 2.0
    scale = (q_scale + ssr) / 2.0
    ref = stats.invgamma(shape, scale=scale)
    for lvl in (0.1, 0.25, 0.5, 0.75, 0.9):
        emp = np.quantile(q_draws, lvl)
        ana = ref.ppf(lvl)
        assert abs(emp - ana) < 0.05 * ana, (lvl, emp, ana)
    # A | Q is centered on the OLS lag-1 coefficient with variance
    # E[Q] * (Z'Z)^-1_{11}
    a_draws = np.asarray(A_d)[:, 0, 0, 0]
    assert abs(a_draws.mean() - ahat[0]) < 4 * a_draws.std() / np.sqrt(4000)
    v11 = np.linalg.inv(zz)[0, 0]
    assert abs(a_draws.std() - np.sqrt(ref.mean() * v11)) < 0.1 * a_draws.std()
