"""Nonlinear particle-filter scenario subsystem (scenarios/particles.py +
scenarios/smc.py): SMC kernels, SV/TVP/Markov-switching density fans, and
the serving + AOT wiring.

The load-bearing pins:

* Kalman parity — the bootstrap filter on the linear-Gaussian companion
  DFM reproduces `kalman_filter`'s loglik and filtered means within
  Monte-Carlo error, and the filtered-mean error shrinks ~1/sqrt(P)
  across P in {256, 1024, 4096};
* golden seeds — the sv.py volatility path, tvp.py loading path, and
  msdfm.py regime probabilities are pinned on fixed-seed panels, and the
  particle kernels agree with those offline estimators on the same data;
* the degenerate-lane drill — a ``nan_draw@k`` injection freezes exactly
  the hit lane and the surviving lanes are BIT-identical to a fault-free
  run (vmap lanes are elementwise), with the clean-path lowering
  carrying no injection code.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.msdfm import fit_ms_dfm, kim_filter
from dynamic_factor_models_tpu.models.ssm import SSMParams, kalman_filter
from dynamic_factor_models_tpu.scenarios import (
    ScenarioRequest,
    ScenarioValidationError,
    run_scenario,
)
from dynamic_factor_models_tpu.scenarios import particles as pk
from dynamic_factor_models_tpu.scenarios import smc
from dynamic_factor_models_tpu.utils import faults

pytestmark = pytest.mark.scenario_nl

# the tier-1 fast-lane particle count: every in-suite filter that is not
# explicitly a convergence-rate check runs at this size
P_FAST = 256


def _lg_params(N=8, r=2, seed=0):
    rng = np.random.default_rng(seed)
    return SSMParams(
        lam=jnp.asarray(rng.standard_normal((N, r))),
        R=jnp.ones(N),
        A=jnp.zeros((2, r, r)).at[0].set(0.4 * jnp.eye(r)),
        Q=jnp.eye(r),
    ), rng


def _lg_panel(params, rng, T=48):
    N, r = params.lam.shape
    lam = np.asarray(params.lam)
    f = np.zeros((T, r))
    for t in range(1, T):
        f[t] = 0.4 * f[t - 1] + rng.standard_normal(r)
    return f @ lam.T + 0.5 * rng.standard_normal((T, N))


# ---------------------------------------------------------------------------
# pure per-step kernels (scenarios/particles.py)
# ---------------------------------------------------------------------------


class TestParticleKernels:
    def test_normalize_logw(self):
        logw = jnp.asarray([0.0, 1.0, 2.0, -1.0])
        n, lse = pk.normalize_logw(logw)
        np.testing.assert_allclose(float(jnp.exp(n).sum()), 1.0, atol=1e-12)
        np.testing.assert_allclose(
            float(lse), float(jax.scipy.special.logsumexp(logw)), atol=1e-12
        )

    def test_ess_bounds(self):
        P = 64
        uniform = jnp.full((P,), -np.log(P))
        np.testing.assert_allclose(float(pk.ess_of(uniform)), P, rtol=1e-10)
        onehot = jnp.full((P,), -1e30).at[3].set(0.0)
        np.testing.assert_allclose(float(pk.ess_of(onehot)), 1.0, rtol=1e-6)

    def test_systematic_indices_proportional(self):
        """Systematic resampling's defining property: every particle is
        copied either floor(P*w) or ceil(P*w) times — strictly tighter
        than multinomial."""
        P = 512
        rng = np.random.default_rng(0)
        w = rng.random(P) + 1e-3
        w = w / w.sum()
        idx = np.asarray(
            pk.systematic_indices(jax.random.PRNGKey(1), jnp.log(w))
        )
        counts = np.bincount(idx, minlength=P)
        assert counts.sum() == P
        assert (counts >= np.floor(P * w)).all()
        assert (counts <= np.ceil(P * w) + 1e-9).all()

    def test_systematic_resample_equalizes(self):
        P = 128
        parts = jnp.arange(P, dtype=float)[:, None]
        logw = jnp.log(jnp.arange(1.0, P + 1.0) / (P * (P + 1) / 2))
        out, lw = pk.systematic_resample(jax.random.PRNGKey(0), parts, logw)
        np.testing.assert_allclose(np.asarray(lw), -np.log(P), atol=1e-12)
        # resampled weighted mean ~ original weighted mean
        m0 = float((jnp.exp(logw) * parts[:, 0]).sum())
        assert abs(float(out[:, 0].mean()) - m0) < 3.0

    def test_adaptive_resample_skips_when_healthy(self):
        P = 64
        parts = jnp.arange(P, dtype=float)[:, None]
        uniform = jnp.full((P,), -np.log(P))
        out, lw, trip, e = pk.adaptive_resample(
            jax.random.PRNGKey(0), parts, uniform, 0.5
        )
        assert not bool(trip)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(parts))
        np.testing.assert_allclose(float(e), P, rtol=1e-10)

    def test_adaptive_resample_trips_when_degenerate(self):
        P = 64
        parts = jnp.arange(P, dtype=float)[:, None]
        logw, _ = pk.normalize_logw(
            jnp.full((P,), -50.0).at[0].set(0.0).at[1].set(-0.5)
        )
        out, lw, trip, e = pk.adaptive_resample(
            jax.random.PRNGKey(0), parts, logw, 0.5
        )
        assert bool(trip) and float(e) < 0.5 * P
        np.testing.assert_allclose(np.asarray(lw), -np.log(P), atol=1e-12)
        assert set(np.asarray(out[:, 0]).tolist()) <= {0.0, 1.0}

    def test_liu_west_jitter_moments(self):
        """Liu-West shrinkage preserves the weighted mean and (by the
        a^2 + h^2 = 1 identity) the weighted variance in expectation."""
        P = 4096
        rng = np.random.default_rng(5)
        theta = jnp.asarray(2.0 + 1.5 * rng.standard_normal((P, 1)))
        logw = jnp.full((P,), -np.log(P))
        out = pk.liu_west_jitter(jax.random.PRNGKey(2), theta, logw)
        assert out.shape == theta.shape
        assert abs(float(out.mean()) - float(theta.mean())) < 0.1
        assert abs(float(out.std()) - float(theta.std())) < 0.1


# ---------------------------------------------------------------------------
# the tentpole pin: SMC on linear-Gaussian == exact Kalman filter
# ---------------------------------------------------------------------------


class TestKalmanParity:
    def test_loglik_within_mc_error(self):
        """At P=4096 the particle marginal likelihood matches the exact
        Kalman loglik within 3 empirical MC standard errors (8 lanes =
        8 independent estimates; their sd estimates the MC error)."""
        params, rng = _lg_params()
        x = _lg_panel(params, rng)
        kf = kalman_filter(params, x)
        res = smc.smc_filter(
            params, x, model="lg", n_particles=4096, n_lanes=8, seed=0
        )
        ll = np.asarray(res.loglik)
        se = ll.std(ddof=1) / np.sqrt(len(ll))
        assert abs(ll.mean() - float(kf.loglik)) < 3.0 * se + 0.05, (
            ll.mean(), float(kf.loglik), se
        )

    def test_filtered_mean_error_shrinks_sqrt_p(self):
        """Filtered-mean error vs the exact Kalman filter decreases
        monotonically in P and shrinks ~1/sqrt(P) (256 -> 4096 is a 4x
        particle-sd ratio; demand at least 2x to leave MC slack)."""
        params, rng = _lg_params()
        x = _lg_panel(params, rng)
        kf_means = np.asarray(kalman_filter(params, x).means)
        errs = {}
        for P in (P_FAST, 1024, 4096):
            res = smc.smc_filter(
                params, x, model="lg", n_particles=P, n_lanes=4, seed=0
            )
            sm = np.asarray(res.summary).mean(axis=0)  # lane-avg (T, k)
            errs[P] = np.abs(sm - kf_means).mean()
        assert errs[1024] < errs[P_FAST], errs
        assert errs[4096] < errs[1024], errs
        assert errs[P_FAST] / errs[4096] > 2.0, errs

    def test_ess_and_resample_telemetry(self):
        params, rng = _lg_params()
        x = _lg_panel(params, rng)
        res = smc.smc_filter(
            params, x, model="lg", n_particles=P_FAST, n_lanes=2
        )
        ess = np.asarray(res.ess)
        assert ess.shape == (2, x.shape[0])
        assert (ess >= 1.0 - 1e-9).all() and (ess <= P_FAST + 1e-6).all()
        assert np.asarray(res.resampled).dtype == bool
        assert (np.asarray(res.health) == 0).all()


# ---------------------------------------------------------------------------
# golden seeds: offline estimators pinned + particle-kernel agreement
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def msdfm_fit():
    rng = np.random.default_rng(3)
    T, N = 160, 8
    Pm = np.array([[0.92, 0.08], [0.12, 0.88]])
    mu = np.array([-1.2, 0.8])
    S = np.zeros(T, int)
    for t in range(1, T):
        S[t] = rng.choice(2, p=Pm[S[t - 1]])
    z = np.zeros(T)
    for t in range(1, T):
        z[t] = 0.6 * z[t - 1] + rng.standard_normal()
    lam = 0.6 + 0.4 * rng.random(N)
    x = np.outer(mu[S] + z, lam) + 0.6 * rng.standard_normal((T, N))
    fit = fit_ms_dfm(x, n_steps=300, n_restarts=1)
    xs = (x - np.asarray(fit.means)) / np.asarray(fit.stds)
    return x, xs, S, fit


class TestGoldenSeeds:
    def test_msdfm_regime_probs_pinned(self, msdfm_fit):
        """Golden seed 3: the fitted regime means, the kim_filter loglik
        and the filtered low-regime frequency are pinned (authoring-time
        values, generous tolerances)."""
        x, xs, S, fit = msdfm_fit
        mu = np.sort(np.asarray(fit.params.mu))
        np.testing.assert_allclose(mu, [-0.775, 1.526], atol=0.35)
        ll, filt_probs, *_ = kim_filter(
            fit.params, jnp.nan_to_num(jnp.asarray(xs)),
            jnp.isfinite(xs).astype(float),
        )
        assert abs(float(ll) - (-918.93)) < 25.0
        fp = np.asarray(filt_probs)
        assert abs(fp[:, 0].mean() - 0.681) < 0.1
        pred0 = fp[:, 0] > 0.5
        acc = max((pred0 == (S == 0)).mean(), (pred0 == (S == 1)).mean())
        assert acc > 0.75, acc

    def test_msdfm_smc_agrees_with_kim_filter(self, msdfm_fit):
        """The regime-switching particle filter reproduces the exact
        Hamilton/Kim recursion's filtered regime probabilities and
        loglik on the fitted model."""
        x, xs, S, fit = msdfm_fit
        ll, filt_probs, *_ = kim_filter(
            fit.params, jnp.nan_to_num(jnp.asarray(xs)),
            jnp.isfinite(xs).astype(float),
        )
        res = smc.smc_filter(
            fit.params, xs, model="msdfm", n_particles=2048, n_lanes=4,
            seed=1,
        )
        M = fit.params.mu.shape[0]
        probs = np.asarray(res.summary)[:, :, 1:1 + M].mean(axis=0)
        assert np.abs(probs - np.asarray(filt_probs)).mean() < 0.05
        ll_lanes = np.asarray(res.loglik)
        se = ll_lanes.std(ddof=1) / np.sqrt(len(ll_lanes))
        assert abs(ll_lanes.mean() - float(ll)) < 3.0 * se + 2.0

    def test_sv_volatility_path_pinned_and_smc_agrees(self):
        """Golden seed 7: estimate_dfm_sv's posterior-mean volatility
        path is pinned (regime separation, level, truth correlation),
        and the SV particle filter at the posterior-mean parameters
        reproduces that path on the standardized panel."""
        from dynamic_factor_models_tpu.models.dfm import DFMConfig
        from dynamic_factor_models_tpu.models.sv import estimate_dfm_sv

        rng = np.random.default_rng(7)
        T, N, r = 160, 8, 1
        h = np.where(np.arange(T) < T // 2, -1.2, 0.6).astype(float)
        ar = np.zeros(T)
        for t in range(1, T):
            ar[t] = 0.9 * ar[t - 1] + 0.2 * rng.standard_normal()
        h = h + ar
        f = np.zeros((T, r))
        for t in range(1, T):
            f[t] = 0.6 * f[t - 1] + np.exp(0.5 * h[t]) * rng.standard_normal(r)
        lam = rng.standard_normal((N, r))
        x = f @ lam.T + 0.4 * rng.standard_normal((T, N))

        res = estimate_dfm_sv(
            jnp.asarray(x), np.ones(N, np.int64), 0, T - 1,
            DFMConfig(nfac_u=1, n_factorlag=1, tol=1e-6, max_iter=200),
            n_keep=80, n_burn=80, n_chains=1, seed=0,
        )
        vol = np.asarray(res.vol_draws).mean(axis=(0, 1))[:, 0]
        true_vol = np.exp(0.5 * h)
        # authoring-time goldens: corr 0.87, late/early 2.12, mean 1.32
        assert np.corrcoef(vol, true_vol)[0, 1] > 0.7
        assert vol[T // 2:].mean() > 1.5 * vol[: T // 2].mean()
        assert abs(vol.mean() - 1.32) < 0.4

        params = SSMParams(
            lam=jnp.asarray(np.asarray(res.lam_draws).mean(axis=(0, 1))),
            R=jnp.asarray(np.asarray(res.r_draws).mean(axis=(0, 1))),
            A=jnp.asarray(np.asarray(res.a_draws).mean(axis=(0, 1))),
            Q=jnp.eye(r),
        )
        aux = tuple(
            jnp.asarray(np.asarray(d).mean(axis=(0, 1)))
            for d in (res.mu_draws, res.phi_draws, res.sig_draws)
        )
        xs = (x - np.asarray(res.means)) / np.asarray(res.stds)
        sres = smc.smc_filter(
            params, xs, model="sv", aux=aux, n_particles=2048, n_lanes=4,
            seed=2,
        )
        k = params.A.shape[0] * r
        smc_vol = np.asarray(sres.summary)[:, :, k:k + r].mean(axis=0)[:, 0]
        # authoring-time: corr(smc, gibbs)=0.88, corr(smc, truth)=0.82
        assert np.corrcoef(smc_vol, vol)[0, 1] > 0.6
        assert np.corrcoef(smc_vol, true_vol)[0, 1] > 0.6

    def test_tvp_loading_path_pinned_and_smc_tracks_break(self):
        """Golden seed 11: tvp_loadings' smoothed path is pinned on a
        mid-sample loading break, and the TVP particle filter tracks the
        same break far better than the static loading."""
        from dynamic_factor_models_tpu.models.tvp import tvp_loadings

        rng = np.random.default_rng(11)
        T, N, r = 200, 6, 1
        F = rng.standard_normal((T, r))
        lam_a = rng.standard_normal((N, r))
        lam_b = lam_a.copy()
        lam_b[N // 2:, 0] += 1.5
        lam_t = np.where(
            np.arange(T)[:, None, None] < T // 2, lam_a, lam_b
        )
        x = np.einsum("tr,tnr->tn", F, lam_t) \
            + 0.3 * rng.standard_normal((T, N))

        res = tvp_loadings(jnp.asarray(x), jnp.asarray(F))
        lp = np.asarray(res.lam_path)
        i = N - 1  # a breaking series
        # authoring-time goldens: early 0.218 (true 0.261), late 1.767
        # (true 1.761), module rmse 0.129
        assert abs(lp[: T // 2 - 20, i, 0].mean() - lam_a[i, 0]) < 0.3
        assert abs(lp[T // 2 + 20:, i, 0].mean() - lam_b[i, 0]) < 0.3
        rmse_tvp = np.sqrt(((lp - lam_t) ** 2).mean())
        assert rmse_tvp < 0.2, rmse_tvp

        params = SSMParams(
            lam=jnp.asarray(lam_a), R=jnp.full(N, 0.09),
            A=jnp.zeros((1, r, r)), Q=jnp.eye(r),
        )
        sres = smc.smc_filter(
            params, x, model="tvp", aux=(jnp.asarray(F), 2e-3),
            n_particles=1024, n_lanes=4, seed=3,
        )
        sl = np.asarray(sres.summary).mean(axis=0).reshape(T, N, r)
        rmse_smc = np.sqrt(((sl - lam_t) ** 2).mean())
        rmse_static = np.sqrt(((lam_a[None] - lam_t) ** 2).mean())
        # authoring-time: smc 0.242 vs static 0.75
        assert rmse_smc < 0.5 * rmse_static, (rmse_smc, rmse_static)


# ---------------------------------------------------------------------------
# degenerate-lane drill (the PR 7 guarded pattern, applied to SMC)
# ---------------------------------------------------------------------------


class TestDegenerateLaneDrill:
    def test_nan_draw_freezes_hit_lane_only(self):
        """nan_draw@5 NaNs lane 0's 5th-step weights: lane 0 freezes
        (health flagged, summary constant from the hit), every sibling
        lane is BIT-identical to the fault-free run."""
        params, rng = _lg_params()
        x = _lg_panel(params, rng)
        kw = dict(model="lg", n_particles=P_FAST, n_lanes=3, horizon=4)
        clean = smc.smc_filter(params, x, **kw)
        with faults.inject("nan_draw@5"):
            hit = smc.smc_filter(params, x, **kw)
        assert hit.health[0] != 0 and (hit.health[1:] == 0).all()
        for fld in ("summary", "ess", "loglik", "bands", "mean", "sd"):
            a = np.asarray(getattr(clean, fld))
            b = np.asarray(getattr(hit, fld))
            np.testing.assert_array_equal(a[1:], b[1:], err_msg=fld)
        # frozen lane repeats its last-good summary after the hit
        s0 = np.asarray(hit.summary)[0]
        assert (s0[6:] == s0[6]).all()
        # ... and diverges from the clean lane's live trajectory
        assert not np.array_equal(s0, np.asarray(clean.summary)[0])

    def test_clean_path_lowering_carries_no_injection_code(self):
        """inject_at is a compile-time static: the clean (inject_at=0)
        lowering is byte-identical inside and outside an armed fault
        context, and differs from an injected lowering — the PR 7
        clean-path-HLO contract."""
        params, rng = _lg_params(N=4, r=1)
        x = _lg_panel(params, rng, T=12)
        yz = jnp.asarray(np.nan_to_num(x))
        mask = jnp.isfinite(jnp.asarray(x))
        keys = jax.random.split(jax.random.PRNGKey(0), 2)
        shocks = jnp.zeros((2, 1))
        q = jnp.asarray(smc.DEFAULT_QUANTILES)
        args = (params, (jnp.zeros((0,)),), keys, yz, mask, shocks, q)
        kw = dict(model="lg", n_particles=32, horizon=0, ess_frac=0.5)

        def lower(inject_at):
            return smc._smc_impl.lower(
                *args, **kw, inject_at=inject_at
            ).as_text()

        clean = lower(0)
        with faults.inject("nan_draw@5"):
            clean_armed = lower(0)
        assert clean == clean_armed
        assert clean != lower(5)


# ---------------------------------------------------------------------------
# request API + serving engine routing
# ---------------------------------------------------------------------------


class TestNonlinearScenarioAPI:
    def test_nowcast_density_returns_bands(self):
        params, rng = _lg_params()
        x = _lg_panel(params, rng)
        res = run_scenario(params, x, ScenarioRequest(
            kind="nowcast_density", model="sv", horizon=4,
            particles=P_FAST,
        ))
        N = params.lam.shape[0]
        assert np.asarray(res.bands).shape == (1, 4, 5, N)
        assert res.quantiles == smc.DEFAULT_QUANTILES
        # quantile bands are monotone in the quantile axis
        b = np.asarray(res.bands)
        assert (np.diff(b, axis=2) >= -1e-9).all()
        assert np.asarray(res.ess).shape == (1, x.shape[0])
        assert float(res.ess_min[0]) >= 1.0
        assert 0.0 <= float(res.resample_rate[0]) <= 1.0
        assert (np.asarray(res.health) == 0).all()

    def test_nowcast_density_custom_quantiles_and_models(self):
        params, rng = _lg_params()
        x = _lg_panel(params, rng)
        for model in ("lg", "tvp"):
            res = run_scenario(params, x, ScenarioRequest(
                kind="nowcast_density", model=model, horizon=2,
                particles=64, quantiles=(0.1, 0.5, 0.9),
            ))
            assert np.asarray(res.bands).shape[2] == 3

    def test_regime_stress_fans_conditional_on_regimes(self, msdfm_fit):
        x, xs, S, fit = msdfm_fit
        params, _ = _lg_params(N=x.shape[1])
        res = run_scenario(params, xs, ScenarioRequest(
            kind="regime_stress", horizon=3, particles=P_FAST,
            shocks=np.array([[0.0], [2.0]]),
            model_config={"msdfm_params": fit.params},
        ))
        assert np.asarray(res.bands).shape[0] == 2
        assert np.asarray(res.regime_probs).shape == (x.shape[0], 2)
        # the shocked lane's median fan sits above the baseline lane's
        b = np.asarray(res.bands)
        assert b[1, 0, 2].mean() != b[0, 0, 2].mean()

    def test_hierarchical_blocks(self):
        params, rng = _lg_params()
        x = _lg_panel(params, rng, T=80)
        res = run_scenario(params, x, ScenarioRequest(
            kind="hierarchical", horizon=4,
            shocks=np.array([[1.0], [-1.0]]),
            blocks=[[0, 1, 2, 3], [4, 5, 6, 7]],
            model_config={"max_outer": 5},
        ))
        assert np.asarray(res.mean).shape == (2, 4, 8)
        assert np.asarray(res.block_means).shape == (2, 4, 2)
        # opposite shocks produce opposite responses
        np.testing.assert_allclose(
            np.asarray(res.mean)[0], -np.asarray(res.mean)[1], atol=1e-9
        )

    def test_validation_errors_name_the_field(self):
        params, rng = _lg_params()
        x = _lg_panel(params, rng)
        cases = [
            (dict(kind="nowcast_density", model="bogus"), "model"),
            (dict(kind="nowcast_density", model="sv", particles=1),
             "particles"),
            (dict(kind="nowcast_density", model="sv", ess_floor=1.5),
             "ess_floor"),
            (dict(kind="nowcast_density", model="sv",
                  quantiles=(0.5, 1.5)), "quantiles"),
            (dict(kind="nowcast_density", model="sv", horizon=0),
             "horizon"),
            (dict(kind="regime_stress", model="sv"), "model"),
            (dict(kind="hierarchical", shocks=np.ones((2, 1))), "blocks"),
            (dict(kind="bogus_kind"), "kind"),
        ]
        for kwargs, field in cases:
            with pytest.raises(ScenarioValidationError) as ei:
                run_scenario(params, x, ScenarioRequest(**kwargs))
            assert ei.value.field == field, (kwargs, ei.value.field)

    def test_engine_routes_nonlinear_kinds(self):
        from dynamic_factor_models_tpu.serving.engine import ServingEngine

        params, rng = _lg_params()
        x = _lg_panel(params, rng)
        eng = ServingEngine()
        eng.register("acme", x)
        res = eng.handle({
            "kind": "scenario", "tenant": "acme",
            "scenario": {"kind": "nowcast_density", "model": "sv",
                         "horizon": 3, "particles": P_FAST},
        })
        assert res.ok
        assert np.asarray(res.result.bands).shape[1] == 3
        assert float(res.result.ess_min[0]) >= 1.0
        # typed client error with the offending field named
        res = eng.handle({
            "kind": "scenario", "tenant": "acme",
            "scenario": {"kind": "nowcast_density", "model": "bogus"},
        })
        assert not res.ok and res.error.category == "client_error"
        assert res.error.code == "bad_scenario"
        assert res.error.field == "scenario.model"
        # pre-existing scenario error contracts are untouched
        res = eng.handle({"kind": "scenario", "tenant": "acme",
                          "scenario": {"kind": "nope"}})
        assert not res.ok and res.error.code == "bad_scenario"
        assert "unknown scenario kind" in res.error.message


# ---------------------------------------------------------------------------
# AOT registration through the transform-stack enumeration
# ---------------------------------------------------------------------------


class TestAOTRegistration:
    def test_particle_count_registers_and_serves_smc(self):
        """precompile(CompileSpec(particle_count=...)) registers the
        three smc_filter@<model> executables via transforms.enumerate_smc
        and matching production calls dispatch to them (aot_hits)."""
        from dynamic_factor_models_tpu.models.msdfm import MSDFMParams
        from dynamic_factor_models_tpu.utils.compile import (
            CompileSpec,
            counters,
            precompile,
        )

        T, N, r = 32, 6, 2
        spec = CompileSpec(
            T=T, N=N, r=r, p=1, dtype=str(np.dtype(float)), bucket=False,
            kernels=(), particle_count=64, scenario_paths=2,
            scenario_horizon=3,
        )
        rep = precompile(spec, warmup=False)
        assert {
            "smc_filter@lg", "smc_filter@sv", "smc_filter@msdfm",
        } <= set(rep["kernels"])

        params, rng = _lg_params(N=N, r=r)
        params = params._replace(
            A=jnp.zeros((1, r, r)).at[0].set(0.4 * jnp.eye(r))
        )
        x = _lg_panel(params, rng, T=T)
        aux_sv = (jnp.zeros(r), jnp.full((r,), 0.95), jnp.full((r,), 0.2))
        for model, aux in (("lg", ()), ("sv", aux_sv)):
            h0 = counters()["smc_filter"]["aot_hits"]
            smc.smc_filter(
                params, x, model=model, aux=aux, n_particles=64,
                n_lanes=2, horizon=3,
            )
            assert counters()["smc_filter"]["aot_hits"] == h0 + 1, model
        mp = MSDFMParams(
            lam=jnp.full(N, 0.5), R=jnp.ones(N),
            mu=jnp.asarray([-1.0, 1.0]), phi=jnp.asarray(0.5),
            P=jnp.asarray([[0.9, 0.1], [0.1, 0.9]]), sigma2=jnp.ones(2),
        )
        h0 = counters()["smc_filter"]["aot_hits"]
        smc.smc_filter(
            mp, x, model="msdfm", n_particles=64,
            shocks=jnp.zeros((2, 1)), horizon=3,
        )
        assert counters()["smc_filter"]["aot_hits"] == h0 + 1

    def test_enumeration_is_additive(self):
        """particle_count=0 (every pre-existing spec) enumerates no SMC
        entries — the new kinds are purely additive to the registry."""
        from dynamic_factor_models_tpu.models import transforms as tfm
        from dynamic_factor_models_tpu.utils.compile import CompileSpec

        spec = CompileSpec(T=32, N=6, r=2, p=1)
        assert tfm.enumerate_smc(spec) == []
