// Native ingest kernels for dynamic_factor_models_tpu.
//
// The biweight local-mean detrend (reference readin_functions.jl:317-348,
// `bi_weight_filter`) is the ingest hot loop: O(T * bandwidth * ns) with a
// per-element missing-aware renormalization.  The NumPy path materializes the
// dense (T, T) weight matrix; this kernel streams the banded window row by
// row (rows are contiguous in the (T, ns) panel), touching each input cell
// O(bandwidth) times with no T x T intermediate.
//
// Built lazily by io/native.py with `g++ -O3 -shared -fPIC`; loaded via
// ctypes (no pybind11 in the image).  Semantics match io/ingest.py
// `_biweight_trend` exactly: tricube-free Tukey biweight 15/16 (1-dt^2)^2 on
// |dt| < 1 with dt = (s - t)/bandwidth, NaN targets stay NaN, weights over
// missing sources are dropped and the kernel renormalized.

#include <algorithm>
#include <cmath>
#include <vector>

extern "C" {

void biweight_trend(const double* data, long T, long ns, double bandwidth,
                    double* out) {
  std::vector<double> num(static_cast<size_t>(ns));
  std::vector<double> den(static_cast<size_t>(ns));
  const long B = static_cast<long>(std::ceil(bandwidth));
  for (long t = 0; t < T; ++t) {
    std::fill(num.begin(), num.end(), 0.0);
    std::fill(den.begin(), den.end(), 0.0);
    const long s0 = std::max(0L, t - B);
    const long s1 = std::min(T - 1, t + B);
    for (long s = s0; s <= s1; ++s) {
      const double dt = static_cast<double>(s - t) / bandwidth;
      const double u = 1.0 - dt * dt;
      if (u <= 0.0) continue;
      const double w = 15.0 / 16.0 * u * u;
      const double* row = data + s * ns;
      // branch-free so the compiler vectorizes: v==v is false only for NaN
      for (long j = 0; j < ns; ++j) {
        const double v = row[j];
        const bool good = (v == v);
        num[static_cast<size_t>(j)] += w * (good ? v : 0.0);
        den[static_cast<size_t>(j)] += good ? w : 0.0;
      }
    }
    double* orow = out + t * ns;
    const double* drow = data + t * ns;
    for (long j = 0; j < ns; ++j) {
      const size_t sj = static_cast<size_t>(j);
      orow[j] = std::isnan(drow[j])
                    ? std::nan("")
                    : (den[sj] > 0.0 ? num[sj] / den[sj] : std::nan(""));
    }
  }
}

}  // extern "C"
